"""osdmaptool-parity CLI.

Covers the reference's ``src/tools/osdmaptool.cc`` placement surface:
``--createsimple N``, ``--print``, ``--test-map-pgs`` (whole-map
mapping + distribution statistics, the batch mapping timer),
``--test-map-object``, ``--upmap`` (run the optimizer, write the
resulting commands), ``--upmap-cleanup``, ``--crush-compat`` (weight-set
descent), ``--mark-out``.  Map files
are the framework's versioned JSON OSDMap encoding.
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from ..osdmap.map import OSDMap, PGId


def load(path: str) -> OSDMap:
    with open(path, "rb") as f:
        return OSDMap.decode(f.read())


def save(m: OSDMap, path: str) -> None:
    with open(path, "wb") as f:
        f.write(m.encode())


def cmd_print(m: OSDMap, out) -> None:
    print(f"epoch {m.epoch}", file=out)
    print(f"max_osd {m.max_osd}", file=out)
    for pid in sorted(m.pools):
        p = m.pools[pid]
        print(
            f"pool {pid} '{p.name}' {p.kind} size {p.size} min_size "
            f"{p.min_size} pg_num {p.pg_num} pgp_num {p.pgp_num} "
            f"crush_rule {p.crush_rule}",
            file=out,
        )
    for osd in range(m.max_osd):
        state = []
        state.append("up" if m.is_up(osd) else "down")
        state.append("out" if m.is_out(osd) else "in")
        w = m.osd_weight[osd] / 0x10000
        print(f"osd.{osd} {' '.join(state)} weight {w:.5f}", file=out)
    for pg, items in sorted(m.pg_upmap_items.items()):
        print(f"pg_upmap_items {pg} {list(map(list, items))}", file=out)


def cmd_test_map_pgs(m: OSDMap, out, pool_id: int | None) -> None:
    from ..osdmap.mapping import OSDMapMapping

    mapping = OSDMapMapping(m)
    pools = [pool_id] if pool_id is not None else sorted(m.pools)
    for pid in pools:  # warm: compile the pool programs
        mapping.update(pid)
    t0 = time.perf_counter()
    for pid in pools:
        mapping.update(pid)
    dt = time.perf_counter() - t0
    counts = np.zeros(max(m.max_osd, 1), np.int64)
    total_pgs = 0
    for pid in pools:
        counts += mapping.pg_counts_by_osd(pid, acting=False)
        total_pgs += m.pools[pid].pg_num
    print(f"pool {','.join(map(str, pools))} pg_num {total_pgs}", file=out)
    print(f"#osd\tcount", file=out)
    for osd in range(m.max_osd):
        print(f"osd.{osd}\t{counts[osd]}", file=out)
    active = counts[[not m.is_out(o) for o in range(m.max_osd)]]
    if len(active):
        print(f"avg {active.mean():.2f} stddev {active.std():.2f}", file=out)
        print(f"min osd count {active.min()} max osd count {active.max()}", file=out)
    print(f"mapping time {dt * 1e3:.1f} ms ({total_pgs / max(dt, 1e-9):.0f} pg/s)", file=out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="osdmaptool")
    p.add_argument("mapfilename")
    p.add_argument("--createsimple", type=int, metavar="NUM_OSD")
    p.add_argument("--pg-num", type=int, default=128)
    p.add_argument("--pool-size", type=int, default=3)
    p.add_argument("--print", dest="do_print", action="store_true")
    p.add_argument("--test-map-pgs", action="store_true")
    p.add_argument("--pool", type=int, default=None)
    p.add_argument("--test-map-object", metavar="NAME")
    p.add_argument("--mark-out", type=int, action="append", metavar="OSD")
    p.add_argument("--upmap", metavar="OUTFILE", help="run the optimizer")
    p.add_argument("--upmap-max", type=int, default=100)
    p.add_argument("--upmap-deviation", type=float, default=1.0)
    p.add_argument("--upmap-pool", action="append", type=int)
    p.add_argument("--upmap-cleanup", action="store_true")
    p.add_argument(
        "--crush-compat", action="store_true",
        help="optimize the compat choose_args weight set instead of upmaps",
    )
    p.add_argument("--save", action="store_true", help="write map changes back")
    args = p.parse_args(argv)
    out = sys.stdout

    if args.createsimple:
        from ..models.clusters import build_osdmap

        m = build_osdmap(
            args.createsimple, pg_num=args.pg_num, size=args.pool_size
        )
        save(m, args.mapfilename)
        print(
            f"osdmaptool: writing epoch {m.epoch} to {args.mapfilename}",
            file=sys.stderr,
        )
        return 0

    m = load(args.mapfilename)
    dirty = False
    if args.mark_out:
        for osd in args.mark_out:
            m.mark_out(osd)
        dirty = True
    if args.do_print:
        cmd_print(m, out)
    if args.test_map_pgs:
        cmd_test_map_pgs(m, out, args.pool)
    if args.test_map_object:
        pool = args.pool if args.pool is not None else sorted(m.pools)[0]
        up, upp, acting, actp = m.map_object(args.test_map_object, pool)
        pg = m.raw_pg_to_pg(m.object_locator_to_pg(args.test_map_object, pool))
        print(
            f" object '{args.test_map_object}' -> {pg} -> up {up} acting {acting}",
            file=out,
        )
    if args.upmap_cleanup:
        removed = len(m.pg_upmap_items) + len(m.pg_upmap)
        m.pg_upmap_items.clear()
        m.pg_upmap.clear()
        print(f"upmap-cleanup: removed {removed} entries", file=out)
        dirty = True
    if args.upmap:
        from ..balancer import calc_pg_upmaps

        inc = calc_pg_upmaps(
            m,
            max_deviation=args.upmap_deviation,
            max_entries=args.upmap_max,
            pools=args.upmap_pool,
        )
        cmds = []
        # entry GC first: the reference emits rm-pg-upmap-items for
        # entries the optimizer retires
        for pg in sorted(inc.old_pg_upmap_items):
            cmds.append(f"ceph osd rm-pg-upmap-items {pg}")
        for pg, items in sorted(inc.new_pg_upmap_items.items()):
            pairs = " ".join(f"{f} {t}" for f, t in items)
            cmds.append(f"ceph osd pg-upmap-items {pg} {pairs}")
        with open(args.upmap, "w") as f:
            f.write("\n".join(cmds) + ("\n" if cmds else ""))
        print(f"upmap: wrote {len(cmds)} commands to {args.upmap}", file=out)
        if cmds:
            m.apply_incremental(inc)
            dirty = True
    if args.crush_compat:
        from ..balancer.module import Balancer

        bal = Balancer(m, mode="crush-compat",
                       max_deviation=args.upmap_deviation)
        before = bal.evaluate(args.upmap_pool)
        changed = bal.tick(args.upmap_pool)  # descends + bumps epoch
        after = bal.evaluate(args.upmap_pool)
        print(
            "crush-compat: "
            f"max deviation {max(before.pool_max_deviation.values(), default=0):.2f}"
            f" -> {max(after.pool_max_deviation.values(), default=0):.2f}"
            f" ({'updated' if changed else 'no change'})",
            file=out,
        )
        dirty = dirty or changed
    if dirty and args.save:
        save(m, args.mapfilename)
        print(f"osdmaptool: writing epoch {m.epoch} to {args.mapfilename}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
