"""Erasure-code benchmark CLI.

Parity with the reference's ``ceph_erasure_code_benchmark``
(``src/test/erasure-code/ceph_erasure_code_benchmark.cc``): encode or
decode workloads per (plugin, technique, k, m, packetsize, size,
iterations), reporting seconds and throughput.

    python -m ceph_tpu.cli.ec_bench --plugin jerasure \
        --workload encode --size 1048576 --iterations 10 \
        --parameter k=8 --parameter m=3
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ec_bench")
    p.add_argument("--plugin", "-p", default="jerasure")
    p.add_argument("--workload", "-w", choices=["encode", "decode"], default="encode")
    p.add_argument("--size", "-s", type=int, default=1 << 20, help="object bytes")
    p.add_argument("--iterations", "-i", type=int, default=10)
    p.add_argument("--erasures", "-e", type=int, default=1)
    p.add_argument(
        "--parameter", "-P", action="append", default=[], metavar="K=V"
    )
    p.add_argument("--verbose", "-v", action="store_true")
    args = p.parse_args(argv)

    from ..ec import ErasureCodeError, create

    profile = {"plugin": args.plugin}
    for kv in args.parameter:
        k, v = kv.split("=", 1)
        profile[k] = v
    try:
        ec = create(profile)
    except ErasureCodeError as e:
        print(f"ec_bench: {e}", file=sys.stderr)
        return 1
    n = ec.get_chunk_count()
    rng = np.random.default_rng(0)
    obj = rng.integers(0, 256, args.size, dtype=np.uint8)

    encoded = ec.encode(set(range(n)), obj)  # warm (compile)
    chunk_size = len(encoded[0])

    if args.workload == "encode":
        t0 = time.perf_counter()
        for _ in range(args.iterations):
            ec.encode(set(range(n)), obj)
        dt = time.perf_counter() - t0
        total = args.size * args.iterations
    else:
        erased = list(range(args.erasures))
        avail = {i: encoded[i] for i in range(n) if i not in erased}
        ec.decode(set(erased), avail, chunk_size)  # warm
        t0 = time.perf_counter()
        for _ in range(args.iterations):
            ec.decode(set(erased), avail, chunk_size)
        dt = time.perf_counter() - t0
        total = args.size * args.iterations
    if args.verbose:
        print(
            f"plugin={args.plugin} profile={profile} chunk_size={chunk_size}",
            file=sys.stderr,
        )
    print(f"{dt:.6f}\t{total / dt / (1 << 20):.2f} MB/s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
