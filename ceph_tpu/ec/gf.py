"""GF(2^8) arithmetic and coding-matrix constructions (host, numpy).

Exact counterpart of ``cpp/gf_ref.cpp`` (primitive polynomial 0x11d),
which itself implements the algebra behind the reference's jerasure
plugin family (upstream ``src/erasure-code/jerasure`` + bundled
``jerasure/jerasure.c`` :: ``reed_sol_vandermonde_coding_matrix``,
``jerasure_matrix_to_bitmatrix``, ``jerasure_matrix_invert`` — spec in
SURVEY.md §2.2).  These tables/matrices are computed once per profile on
the host; the bulk byte work happens on device
(:mod:`ceph_tpu.ec.backend`).
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

PRIM_POLY = 0x11D
W = 8


@lru_cache(maxsize=1)
def tables() -> tuple[np.ndarray, np.ndarray]:
    """(log, exp) tables; exp has 510 entries so log[a]+log[b] indexes it."""
    log = np.zeros(256, np.int32)
    exp = np.zeros(510, np.uint8)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= PRIM_POLY
    exp[255:510] = exp[0:255]
    log[0] = 0  # undefined; callers must special-case 0
    return log, exp


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    log, exp = tables()
    return int(exp[log[a] + log[b]])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("gf_inv(0)")
    log, exp = tables()
    return int(exp[255 - log[a]])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("gf_div by 0")
    if a == 0:
        return 0
    log, exp = tables()
    return int(exp[(log[a] + 255 - log[b]) % 255])


@lru_cache(maxsize=1)
def mul_table() -> np.ndarray:
    """Full 256x256 product table (device gather operand)."""
    log, exp = tables()
    a = np.arange(256)
    t = exp[(log[a][:, None] + log[a][None, :])]
    t[0, :] = 0
    t[:, 0] = 0
    return t.astype(np.uint8)


def mul_region(c: int, data: np.ndarray) -> np.ndarray:
    """c * data elementwise over GF(2^8) (vectorized host)."""
    return mul_table()[c][data]


# ---- coding matrices (all m x k over GF(2^8)) ----


def vandermonde_matrix(k: int, m: int) -> np.ndarray:
    """reed_sol_van semantics: extended Vandermonde systematized so the
    top k x k block is the identity; returns the bottom m rows."""
    rows = k + m
    if rows > 256:
        raise ValueError("k + m must be <= 256 for w=8")
    v = np.zeros((rows, k), np.uint8)
    v[0, 0] = 1
    for i in range(1, rows - 1):
        e = 1
        for j in range(k):
            v[i, j] = e
            e = gf_mul(e, i)
    v[rows - 1, k - 1] = 1
    # systematize by column operations (mirrors gfref_vandermonde_matrix)
    for i in range(1, k):
        pr = next((r for r in range(i, rows) if v[r, i] != 0), None)
        if pr is None:
            raise ValueError("singular vandermonde block")
        if pr != i:
            v[[pr, i]] = v[[i, pr]]
        if v[i, i] != 1:
            inv = gf_div(1, int(v[i, i]))
            v[:, i] = mul_region(inv, v[:, i])
        for j in range(k):
            f = int(v[i, j])
            if j != i and f != 0:
                v[:, j] ^= mul_region(f, v[:, i])
    return v[k:].copy()


def raid6_matrix(k: int) -> np.ndarray:
    """reed_sol_r6_op semantics: P = XOR row, Q = powers of alpha."""
    out = np.zeros((2, k), np.uint8)
    e = 1
    for j in range(k):
        out[0, j] = 1
        out[1, j] = e
        e = gf_mul(e, 2)
    return out


def cauchy_matrix(k: int, m: int) -> np.ndarray:
    """Original Cauchy: M[i][j] = 1 / (i XOR (m + j))."""
    if k + m > 256:
        raise ValueError("k + m must be <= 256 for w=8")
    out = np.zeros((m, k), np.uint8)
    for i in range(m):
        for j in range(k):
            d = i ^ (m + j)
            if d == 0:
                raise ValueError("cauchy index collision")
            out[i, j] = gf_inv(d)
    return out


def cauchy_good_matrix(k: int, m: int) -> np.ndarray:
    """cauchy_good semantics: original Cauchy improved so row 0 and
    column 0 are all ones (divide each column by its row-0 element,
    then normalize each row by its column-0 element) — jerasure
    ``cauchy_original_coding_matrix`` + ``improve_coding_matrix``."""
    mat = cauchy_matrix(k, m)
    for j in range(k):
        f = int(mat[0, j])
        if f != 1:
            mat[:, j] = mul_region(gf_inv(f), mat[:, j])
    for i in range(1, m):
        f = int(mat[i, 0])
        if f != 1:
            mat[i] = mul_region(gf_inv(f), mat[i])
    return mat


def invert_matrix(mat: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inverse over GF(2^8); raises on singular."""
    k = mat.shape[0]
    a = mat.astype(np.uint8).copy()
    inv = np.eye(k, dtype=np.uint8)
    for col in range(k):
        pr = next((r for r in range(col, k) if a[r, col] != 0), None)
        if pr is None:
            raise ValueError("singular matrix")
        if pr != col:
            a[[pr, col]] = a[[col, pr]]
            inv[[pr, col]] = inv[[col, pr]]
        piv = int(a[col, col])
        if piv != 1:
            f = gf_inv(piv)
            a[col] = mul_region(f, a[col])
            inv[col] = mul_region(f, inv[col])
        for r in range(k):
            f = int(a[r, col])
            if r != col and f != 0:
                a[r] ^= mul_region(f, a[col])
                inv[r] ^= mul_region(f, inv[col])
    return inv


def matrix_encode(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Host reference encode: data [k, size] u8 -> coding [m, size]."""
    m, k = matrix.shape
    assert data.shape[0] == k
    mt = mul_table()
    out = np.zeros((m, data.shape[1]), np.uint8)
    for i in range(m):
        acc = out[i]
        for j in range(k):
            e = int(matrix[i, j])
            if e == 0:
                continue
            acc ^= mt[e][data[j]]
    return out


# ---- GF(2) bit-matrix forms (the MXU-friendly representation) ----


def matrix_to_bitmatrix(matrix: np.ndarray) -> np.ndarray:
    """Expand m x k GF(2^8) to (m*8) x (k*8) GF(2): block (i,j) column l
    holds the bits of M[i][j] * alpha^l."""
    m, k = matrix.shape
    out = np.zeros((m * W, k * W), np.uint8)
    for i in range(m):
        for j in range(k):
            e = int(matrix[i, j])
            for l in range(W):
                for t in range(W):
                    out[i * W + t, j * W + l] = (e >> t) & 1
                e = gf_mul(e, 2)
    return out


def bitmatrix_multiply(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(2) matrix product: exact integer matmul, parity = & 1.

    Because ``matrix_to_bitmatrix`` is a ring homomorphism (companion-
    matrix representation of GF(2^8)), composing repair matrices here
    is byte-identical to composing them over GF(2^8) and expanding.
    """
    prod = (a & 1).astype(np.int64) @ (b & 1).astype(np.int64)
    return (prod & 1).astype(np.uint8)


def invert_bitmatrix(mat: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inverse over GF(2); raises on singular."""
    n = mat.shape[0]
    a = (mat & 1).astype(np.uint8).copy()
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        pr = next((r for r in range(col, n) if a[r, col]), None)
        if pr is None:
            raise ValueError("singular bitmatrix")
        if pr != col:
            a[[pr, col]] = a[[col, pr]]
            inv[[pr, col]] = inv[[col, pr]]
        for r in range(n):
            if r != col and a[r, col]:
                a[r] ^= a[col]
                inv[r] ^= inv[col]
    return inv


def bitmatrix_encode(
    bitmatrix: np.ndarray, data: np.ndarray, packetsize: int
) -> np.ndarray:
    """Host reference bitmatrix encode with packet interleaving.

    Each chunk is groups of 8 packets of ``packetsize`` bytes; parity
    packet (i, t) of each group = XOR of data packets (j, l) where
    bitmatrix[i*8+t, j*8+l] == 1.  size must divide into 8*packetsize
    groups.
    """
    mw, kw = bitmatrix.shape
    k, m = kw // W, mw // W
    size = data.shape[1]
    group = W * packetsize
    assert size % group == 0, (size, group)
    ngroups = size // group
    d = data.reshape(k, ngroups, W, packetsize)
    c = np.zeros((m, ngroups, W, packetsize), np.uint8)
    for i in range(m):
        for t in range(W):
            row = bitmatrix[i * W + t]
            for j in range(k):
                for l in range(W):
                    if row[j * W + l]:
                        c[i, :, t, :] ^= d[j, :, l, :]
    return c.reshape(m, size)
