"""Striped-object layer over the EC plugins (ECUtil parity).

The reference's ``src/osd/ECUtil.h :: stripe_info_t`` carries the
stripe geometry ECBackend uses to address objects on shards:
``stripe_width = k * chunk_size``, logical<->chunk offset conversion,
and stripe-aligned rounding.  :class:`StripeInfo` mirrors that API;
:func:`encode_object` / :func:`decode_object` implement the multi-
stripe object path on top of it (the part of
``src/osd/ECBackend.cc :: submit_transaction / objects_read_async``
that turns whole objects into per-shard streams and back, including
chunk->shard mapping application and re-selection of the read set when
a shard fails mid-recovery — the
``qa/standalone/erasure-code/test-erasure-eio.sh`` scenario).

TPU-first design: the reference iterates stripes, calling
``encode_chunks`` per stripe.  Every device codec here is byte/packet
local along the chunk axis and ``chunk_size`` is alignment-divisible,
so a shard's stream (its chunks concatenated across all stripes) can
be encoded or decoded in ONE ``encode_chunks``/``decode_chunks`` call
over the whole object — stripes become batch width, not a loop.
"""

from __future__ import annotations

import numpy as np

from .interface import ErasureCode, ErasureCodeError


class StripeInfo:
    """``ECUtil::stripe_info_t`` analog: stripe geometry + conversions."""

    def __init__(self, k: int, chunk_size: int):
        if chunk_size <= 0 or k <= 0:
            raise ValueError("k and chunk_size must be positive")
        self.k = k
        self.chunk_size = chunk_size
        self.stripe_width = k * chunk_size

    # ---- reference stripe_info_t API ----

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - (offset % self.stripe_width)

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        return -(-offset // self.stripe_width) * self.stripe_width

    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return -(-offset // self.stripe_width) * self.chunk_size

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        assert offset % self.stripe_width == 0, offset
        return offset // self.k

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        assert offset % self.chunk_size == 0, offset
        return offset * self.k

    def offset_len_to_stripe_bounds(
        self, offset: int, length: int
    ) -> tuple[int, int]:
        """Smallest stripe-aligned (offset, length) covering the range."""
        start = self.logical_to_prev_stripe_offset(offset)
        end = self.logical_to_next_stripe_offset(offset + length)
        return start, end - start

    def object_stripes(self, object_size: int) -> int:
        return -(-object_size // self.stripe_width) if object_size else 0


def stripe_info_for(ec: ErasureCode, stripe_unit_width: int) -> StripeInfo:
    """Geometry for a pool whose stripe width is ``stripe_unit_width``
    logical bytes (the reference derives chunk_size through the
    plugin's alignment the same way)."""
    return StripeInfo(
        ec.get_data_chunk_count(), ec.get_chunk_size(stripe_unit_width)
    )


def _shard_map(ec: ErasureCode) -> list[int]:
    """raw chunk index -> shard id (identity when the plugin declares
    no mapping)."""
    mapping = ec.get_chunk_mapping()
    return mapping if mapping else list(range(ec.get_chunk_count()))


def encode_object(
    ec: ErasureCode, data: bytes | np.ndarray, stripe_width: int
) -> tuple[StripeInfo, dict[int, np.ndarray]]:
    """Encode a whole (multi-stripe) object into per-shard streams.

    Logical byte ``o`` lives in stripe ``o // stripe_width``, raw chunk
    ``(o % stripe_width) // chunk_size`` — the ECBackend layout.  The
    object is zero-padded to a whole number of stripes; shard ``s``'s
    stream is its chunk from every stripe, concatenated.  One device
    encode call covers all stripes.

    Returns (stripe info, {shard id: stream}).
    """
    if isinstance(data, (bytes, bytearray)):
        data = np.frombuffer(bytes(data), np.uint8)
    sinfo = stripe_info_for(ec, stripe_width)
    k, m = ec.get_data_chunk_count(), ec.get_coding_chunk_count()
    shard = _shard_map(ec)
    n_stripes = max(sinfo.object_stripes(len(data)), 1)
    padded = np.zeros(n_stripes * sinfo.stripe_width, np.uint8)
    padded[: len(data)] = data
    # [n_stripes, k, chunk] -> raw chunk j's stream = [:, j, :] flattened
    view = padded.reshape(n_stripes, k, sinfo.chunk_size)
    chunks: dict[int, np.ndarray] = {}
    for j in range(k):
        chunks[shard[j]] = np.ascontiguousarray(view[:, j, :]).reshape(-1)
    stream_len = n_stripes * sinfo.chunk_size
    for j in range(k, k + m):
        chunks[shard[j]] = np.zeros(stream_len, np.uint8)
    ec.encode_chunks(chunks)
    return sinfo, chunks


def decode_object(
    ec: ErasureCode,
    sinfo: StripeInfo,
    shards: dict[int, np.ndarray],
    object_size: int,
    failed: set[int] | None = None,
) -> bytes:
    """Reassemble an object from (a subset of) its shard streams.

    ``failed`` marks shards whose reads errored after being selected
    (the EIO scenario): they are excluded and the minimum read set is
    re-selected from what remains, exactly like ECBackend re-issuing
    recovery reads.  Raises ErasureCodeError when fewer than k shards
    remain.
    """
    failed = set(failed or ())
    avail = {s: v for s, v in shards.items() if s not in failed}
    k = ec.get_data_chunk_count()
    shard = _shard_map(ec)
    want = {shard[j] for j in range(k)}
    need = ec.minimum_to_decode(want, set(avail))
    if not need <= set(avail):
        raise ErasureCodeError(f"minimum set {need} not available")
    n_stripes = max(sinfo.object_stripes(object_size), 1)
    stream_len = n_stripes * sinfo.chunk_size
    for s in need:
        if len(avail[s]) != stream_len:
            raise ErasureCodeError(
                f"shard {s}: stream length {len(avail[s])} != {stream_len}"
            )
    decoded = ec.decode(want, {s: avail[s] for s in need}, stream_len)
    out = np.empty((n_stripes, k, sinfo.chunk_size), np.uint8)
    for j in range(k):
        out[:, j, :] = decoded[shard[j]].reshape(
            n_stripes, sinfo.chunk_size
        )
    return out.reshape(-1)[:object_size].tobytes()
