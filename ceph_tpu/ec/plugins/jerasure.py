"""Reed-Solomon / Cauchy codec family (jerasure-plugin parity).

Technique semantics follow the reference's
``src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc}`` classes:

- ``reed_sol_van``  — Vandermonde RS over GF(2^8) (matrix technique)
- ``reed_sol_r6_op``— RAID6 P+Q (m must be 2)
- ``cauchy_orig``   — original Cauchy bit-matrix
- ``cauchy_good``   — improved Cauchy bit-matrix (jerasure
  ``cauchy_good`` matrix optimization)

Matrix techniques run on device through :class:`TableEncoder`;
bit-matrix techniques through the MXU :class:`BitmatrixEncoder`
(packetsize-interleaved, ``jerasure_schedule_encode`` layout).  The
``liberation``/``blaum_roth``/``liber8tion`` minimal-density codes use
w in {7, 11, ...} and are not yet implemented (profile raises).
"""

from __future__ import annotations

import numpy as np

from .. import gf
from ..backend import MatrixCodec
from ..interface import ErasureCode, ErasureCodeError, Profile

MATRIX_TECHNIQUES = ("reed_sol_van", "reed_sol_r6_op")
BITMATRIX_TECHNIQUES = ("cauchy_orig", "cauchy_good")
SIZEOF_INT = 4


class ErasureCodeJerasure(ErasureCode):
    technique = "reed_sol_van"

    def init(self, profile: Profile) -> None:
        self.profile = profile
        self.k = profile.get_int("k", 2)
        self.m = profile.get_int("m", 1)
        self.w = profile.get_int("w", 8)
        self.technique = profile.get("technique", "reed_sol_van")
        self.packetsize = profile.get_int("packetsize", 2048)
        if self.w != 8:
            raise ErasureCodeError(
                f"w={self.w} unsupported: the device GF kernels are w=8 "
                "(the reference's default)"
            )
        if self.k < 1 or self.m < 1 or self.k + self.m > 256:
            raise ErasureCodeError(f"bad k={self.k} m={self.m}")
        if self.technique == "reed_sol_van":
            matrix = gf.vandermonde_matrix(self.k, self.m)
        elif self.technique == "reed_sol_r6_op":
            if self.m != 2:
                raise ErasureCodeError("reed_sol_r6_op requires m=2")
            matrix = gf.raid6_matrix(self.k)
        elif self.technique == "cauchy_orig":
            matrix = gf.cauchy_matrix(self.k, self.m)
        elif self.technique == "cauchy_good":
            matrix = gf.cauchy_good_matrix(self.k, self.m)
        else:
            raise ErasureCodeError(
                f"technique {self.technique!r} not implemented"
            )
        kind = (
            "bitmatrix" if self.technique in BITMATRIX_TECHNIQUES else "table"
        )
        self.codec = MatrixCodec(matrix, kind, self.packetsize)

    def get_alignment(self) -> int:
        if self.technique in BITMATRIX_TECHNIQUES:
            # reference ErasureCodeJerasureCauchy::get_alignment is
            # k * w * packetsize * sizeof(int) — the extra sizeof(int)
            # factor matters for on-disk chunk-size parity
            return self.k * self.w * self.packetsize * SIZEOF_INT
        return self.k * self.w * SIZEOF_INT

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        data = np.stack([chunks[i] for i in range(self.k)])
        coding = self.codec.encode(data)
        for i in range(self.m):
            chunks[self.k + i][:] = coding[i]

    def decode_chunks(
        self, want_to_read: set[int], chunks: dict[int, np.ndarray]
    ) -> dict[int, np.ndarray]:
        return self.codec.decode(dict(chunks), set(want_to_read))
