"""Reed-Solomon / Cauchy / minimal-density codec family (jerasure-plugin
parity).

Technique semantics follow the reference's
``src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc}`` classes:

- ``reed_sol_van``   — Vandermonde RS over GF(2^w), w in {8, 16, 32}
- ``reed_sol_r6_op`` — RAID6 P+Q (m must be 2), w in {8, 16, 32}
- ``cauchy_orig``    — original Cauchy bit-matrix, w in {8, 16, 32}
- ``cauchy_good``    — improved Cauchy bit-matrix, w in {8, 16, 32}
- ``liberation``     — minimal-density RAID-6, w prime (e.g. 7, 11, 13)
- ``blaum_roth``     — minimal-density RAID-6, w+1 prime (e.g. 6, 10, 12)
- ``liber8tion``     — minimal-density RAID-6, w = 8, m = 2

Execution strategy (TPU-first, not gf-complete's):

- w=8 matrix techniques run on device through :class:`TableEncoder`
  (GF(2^8) LUT gathers); w=8 cauchy through the MXU
  :class:`BitmatrixEncoder`.
- Every w>8 technique and every minimal-density code is expanded once
  (host) to its GF(2) bit-matrix and runs as an int8 MXU matmul
  (:class:`BitmatrixCodec`) — the TPU has no SIMD GF(2^16)/GF(2^32)
  table path worth emulating, but GF(2) dot is native MXU work.
  Deviation notes (parameters and erasure tolerance identical in all
  cases; exact bytes pinned by the non-regression archive; re-verify
  against the reference mount when it returns):

  - for w>8 *matrix* techniques the on-wire chunk layout is the
    bit-sliced packet layout of the bitmatrix path, not gf-complete's
    contiguous w-bit-word region layout;
  - ``liber8tion`` Q-parity bytes come from in-repo block matrices
    (a deterministic search for k<=6, companion-matrix powers for
    k in {7,8}), not Plank's published search results, so that parity
    chunk is not byte-interchangeable with upstream jerasure's.
"""

from __future__ import annotations

import numpy as np

from .. import gf, gfw
from ..backend import BitmatrixCodec, MatrixCodec
from ..interface import ErasureCode, ErasureCodeError, Profile

MATRIX_TECHNIQUES = ("reed_sol_van", "reed_sol_r6_op")
BITMATRIX_TECHNIQUES = ("cauchy_orig", "cauchy_good")
MINDENSITY_TECHNIQUES = ("liberation", "blaum_roth", "liber8tion")
SIZEOF_INT = 4


class ErasureCodeJerasure(ErasureCode):
    technique = "reed_sol_van"

    def init(self, profile: Profile) -> None:
        self.profile = profile
        self.k = profile.get_int("k", 2)
        self.m = profile.get_int("m", 1)
        self.technique = profile.get("technique", "reed_sol_van")
        self.w = profile.get_int(
            "w", 7 if self.technique == "liberation" else 8
        )
        self.packetsize = profile.get_int("packetsize", 2048)
        if self.k < 1 or self.m < 1:
            raise ErasureCodeError(f"bad k={self.k} m={self.m}")
        t, w = self.technique, self.w
        if t in MINDENSITY_TECHNIQUES:
            if self.m != 2:
                raise ErasureCodeError(f"{t} requires m=2 (RAID-6)")
            kmax = 8 if t == "liber8tion" else w
            if self.k > kmax:
                raise ErasureCodeError(f"{t} requires k <= w ({self.k} > {kmax})")
            try:
                bm = np.frombuffer(
                    gfw.bitmatrix_for(t, self.k, 2, 8 if t == "liber8tion" else w),
                    np.uint8,
                ).reshape(2 * (8 if t == "liber8tion" else w), -1)
            except ValueError as e:
                raise ErasureCodeError(str(e)) from e
            if t == "liber8tion":
                self.w = w = 8
            self.codec = BitmatrixCodec(bm.copy(), w, self.packetsize)
        elif w == 8:
            if self.k + self.m > 256:
                raise ErasureCodeError(f"k+m > 256 for w=8")
            if t == "reed_sol_van":
                matrix = gf.vandermonde_matrix(self.k, self.m)
            elif t == "reed_sol_r6_op":
                if self.m != 2:
                    raise ErasureCodeError("reed_sol_r6_op requires m=2")
                matrix = gf.raid6_matrix(self.k)
            elif t == "cauchy_orig":
                matrix = gf.cauchy_matrix(self.k, self.m)
            elif t == "cauchy_good":
                matrix = gf.cauchy_good_matrix(self.k, self.m)
            else:
                raise ErasureCodeError(f"technique {t!r} not implemented")
            kind = "bitmatrix" if t in BITMATRIX_TECHNIQUES else "table"
            self.codec = MatrixCodec(matrix, kind, self.packetsize)
        elif w in (16, 32):
            if self.k + self.m > (1 << w):
                raise ErasureCodeError(f"k+m > 2^{w}")
            if t == "reed_sol_van":
                matrix = gfw.vandermonde_matrix(self.k, self.m, w)
            elif t == "reed_sol_r6_op":
                if self.m != 2:
                    raise ErasureCodeError("reed_sol_r6_op requires m=2")
                matrix = gfw.raid6_matrix(self.k, w)
            elif t == "cauchy_orig":
                matrix = gfw.cauchy_matrix(self.k, self.m, w)
            elif t == "cauchy_good":
                matrix = gfw.cauchy_good_matrix(self.k, self.m, w)
            else:
                raise ErasureCodeError(f"technique {t!r} not implemented")
            bm = gfw.matrix_to_bitmatrix(matrix, w)
            # matrix techniques carry no packetsize in the reference's
            # alignment (k*w*sizeof(int)); run the bitmatrix path with
            # packetsize = sizeof(int) so chunk granularity matches
            ps = (
                self.packetsize
                if t in BITMATRIX_TECHNIQUES
                else SIZEOF_INT
            )
            self.codec = BitmatrixCodec(bm, w, ps)
        else:
            raise ErasureCodeError(
                f"w={w} unsupported (8/16/32 for matrix/cauchy; prime w "
                "for liberation; w+1 prime for blaum_roth; 8 for "
                "liber8tion)"
            )

    def get_alignment(self) -> int:
        # reference per-class get_alignment: matrix techniques are
        # k*w*sizeof(int); packetsize-schedule techniques (cauchy +
        # minimal-density) add the packetsize factor
        if self.technique in MATRIX_TECHNIQUES:
            return self.k * self.w * SIZEOF_INT
        return self.k * self.w * self.packetsize * SIZEOF_INT

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        data = np.stack([chunks[i] for i in range(self.k)])
        coding = self.codec.encode(data)
        for i in range(self.m):
            chunks[self.k + i][:] = coding[i]

    def decode_chunks(
        self, want_to_read: set[int], chunks: dict[int, np.ndarray]
    ) -> dict[int, np.ndarray]:
        return self.codec.decode(dict(chunks), set(want_to_read))
