"""Locally-repairable codes (LRC plugin parity).

Semantics follow the reference's ``src/erasure-code/lrc/ErasureCodeLrc.{h,cc}``:
a *mapping* string assigns global chunk positions ('D' = data, anything
else = coding) and *layers* are inner codes, each applied to the subset
of positions its descriptor selects ('D' = layer data, 'c' = layer
coding, '_' = not in this layer).  A single lost chunk is repaired from
its smallest covering layer (the locality win); larger failures fall
back to wider layers.

Both the generic ``mapping``/``layers`` profile and the simplified
``k``/``m``/``l`` generator are supported.  With k/m/l, the layout is
the reference's: one global layer (k data + m RS parities) followed by
one XOR local parity per group of ``l`` consecutive data+global
positions — total chunks k + m + (k+m)/l.

Inner codes are built through the plugin registry, so layer profiles
may name any registered plugin (default jerasure reed_sol_van).
"""

from __future__ import annotations

import json

import numpy as np

from ..interface import ErasureCode, ErasureCodeError, Profile


class _Layer:
    def __init__(self, descriptor: str, profile: dict[str, str]):
        self.descriptor = descriptor
        # global positions participating in this layer, in order
        self.positions = [i for i, c in enumerate(descriptor) if c != "_"]
        self.data_pos = [i for i in self.positions if descriptor[i] == "D"]
        self.coding_pos = [i for i in self.positions if descriptor[i] != "D"]
        prof = dict(profile)
        prof.setdefault("plugin", "jerasure")
        prof["k"] = str(len(self.data_pos))
        prof["m"] = str(len(self.coding_pos))
        from ..registry import create

        self.ec = create(prof)

    def encode(self, chunks: dict[int, np.ndarray]) -> None:
        """Fill this layer's coding positions from its data positions.

        Layer-local ids: data first (order of 'D' positions), then
        coding — remapped to the inner code's 0..k-1 / k..k+m-1.
        """
        k = len(self.data_pos)
        inner = {j: chunks[p] for j, p in enumerate(self.data_pos)}
        for j, p in enumerate(self.coding_pos):
            inner[k + j] = chunks[p]
        self.ec.encode_chunks(inner)
        for j, p in enumerate(self.coding_pos):
            chunks[p][:] = inner[k + j]

    def repair(
        self, chunks: dict[int, np.ndarray], erased: set[int], size: int
    ) -> None:
        k = len(self.data_pos)
        ids = self.data_pos + self.coding_pos
        avail = {
            j: chunks[p] for j, p in enumerate(ids) if p not in erased
        }
        want = {j for j, p in enumerate(ids) if p in erased}
        decoded = self.ec.decode_chunks(want, avail)
        for j, p in enumerate(ids):
            if p in erased:
                chunks[p] = decoded[j]
                erased.discard(p)


class ErasureCodeLrc(ErasureCode):
    def init(self, profile: Profile) -> None:
        self.profile = profile
        if "mapping" in profile:
            mapping = profile["mapping"]
            layers_spec = json.loads(profile["layers"])
        else:
            mapping, layers_spec = self._generate(
                profile.get_int("k", 4),
                profile.get_int("m", 2),
                profile.get_int("l", 3),
            )
        self.mapping = mapping
        self.layers = [
            _Layer(desc, prof if isinstance(prof, dict) else {})
            for desc, prof in layers_spec
        ]
        n = len(mapping)
        self.k = sum(1 for c in mapping if c == "D")
        self.m = n - self.k
        # base-class chunk_mapping from the 'D'/'_' string: raw chunk i
        # (0..k-1 data, k.. coding) -> global shard position; serves
        # get_chunk_mapping and _chunk_index
        dp = self._data_positions()
        self.chunk_mapping = dp + [p for p in range(n) if p not in dp]
        for layer in self.layers:
            if len(layer.descriptor) != n:
                raise ErasureCodeError(
                    f"layer {layer.descriptor!r} length != mapping {mapping!r}"
                )

    @staticmethod
    def _generate(k: int, m: int, l: int):
        """k/m/l layout: k data, m global RS, (k+m)/l local XOR parities.

        Matches the reference's generated layout (parities at the START
        of each group): each group of l+1 positions is [local parity,
        global parities..., data...], e.g. k=4 m=2 l=3 -> mapping
        ``__DD__DD``, layers ``_cDD_cDD`` / ``cDDD____`` / ``____cDDD``
        (upstream ``src/erasure-code/lrc/ErasureCodeLrc.cc`` parse_kml,
        doc/rados/operations/erasure-code-lrc.rst example).
        """
        if (k + m) % l:
            raise ErasureCodeError(f"k+m={k + m} must be divisible by l={l}")
        groups = (k + m) // l
        # distribute the m global parities over groups, earliest first
        per = [m // groups + (1 if g < m % groups else 0) for g in range(groups)]
        n = k + m + groups
        mapping = ""
        global_desc = ""
        local_descs = []
        for g in range(groups):
            ncod = per[g]
            mapping += "_" + "_" * ncod + "D" * (l - ncod)
            global_desc += "_" + "c" * ncod + "D" * (l - ncod)
            local = ["_"] * n
            base = g * (l + 1)
            local[base] = "c"
            for i in range(1, l + 1):
                local[base + i] = "D"
            local_descs.append("".join(local))
        layers = [[global_desc, {"plugin": "jerasure", "technique": "reed_sol_van"}]]
        for d in local_descs:
            layers.append([d, {"plugin": "jerasure", "technique": "reed_sol_van"}])
        return mapping, layers

    def get_chunk_count(self) -> int:
        return len(self.mapping)

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_alignment(self) -> int:
        # chunks are shared across layers, so per-chunk alignment of
        # w * sizeof(int) = 32 covers every inner matrix code
        return self.k * 32

    def _data_positions(self) -> list[int]:
        return [i for i, c in enumerate(self.mapping) if c == "D"]

    def create_rule(self, name: str, crush_map):
        """LRC's own rule builder (upstream ErasureCodeLrc::create_rule):
        the profile's ``crush-steps`` JSON — a list of
        ``[op, type, num]`` with op choose|chooseleaf — replaces the
        base's single chooseleaf step, so chunks land grouped by
        locality (e.g. pick 3 racks, then 4 hosts in each)."""
        from ...crush.map import (
            OP_CHOOSE_INDEP,
            OP_CHOOSELEAF_INDEP,
            OP_EMIT,
            OP_SET_CHOOSELEAF_TRIES,
            OP_TAKE,
            Step,
        )

        profile = getattr(self, "profile", None) or Profile()
        root, fd, dc = self._rule_profile()
        try:
            steps_spec = json.loads(
                profile.get("crush-steps", '[["chooseleaf", "%s", 0]]' % fd)
            )
            if not isinstance(steps_spec, list):
                raise ErasureCodeError(
                    f"crush-steps must be a JSON list, got {steps_spec!r}"
                )
            root_id = crush_map._resolve_take(root, dc)
            steps = [Step(OP_SET_CHOOSELEAF_TRIES, 5), Step(OP_TAKE, root_id)]
            for spec in steps_spec:
                if (
                    not isinstance(spec, (list, tuple))
                    or len(spec) != 3
                    or spec[0] not in ("choose", "chooseleaf")
                ):
                    raise ErasureCodeError(
                        f"crush-steps entry {spec!r} must be "
                        "[choose|chooseleaf, type, num]"
                    )
                op, type_name, num = spec
                opcode = (
                    OP_CHOOSELEAF_INDEP if op == "chooseleaf"
                    else OP_CHOOSE_INDEP
                )
                steps.append(
                    Step(opcode, int(num), crush_map.type_id(type_name))
                )
            steps.append(Step(OP_EMIT))
            return crush_map.add_rule(name, steps, kind="erasure")
        except (KeyError, ValueError, TypeError, json.JSONDecodeError) as e:
            raise ErasureCodeError(f"create_rule {name!r}: {e}") from e


    def encode_prepare(self, data: np.ndarray) -> dict[int, np.ndarray]:
        blocksize = self.get_chunk_size(len(data))
        chunks: dict[int, np.ndarray] = {
            p: np.zeros(blocksize, np.uint8)
            for p in range(len(self.mapping))
        }
        dp = self._data_positions()
        for i in range(self.k):
            lo = i * blocksize
            hi = min(len(data), (i + 1) * blocksize)
            if hi > lo:
                chunks[dp[i]][: hi - lo] = data[lo:hi]
        return chunks

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        for layer in self.layers:
            layer.encode(chunks)

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> set[int]:
        """Walk the layer structure the way decode_chunks will, smallest
        layers first (locality: a single lost chunk reads only its local
        group), accumulating the read set each repair needs — and raise
        when no repair chain reaches the wanted chunks.  Mirroring the
        decode iteration exactly keeps the claim and the decode in
        lockstep (LRC is not MDS: "any k available" is NOT sufficient,
        upstream ``ErasureCodeLrc::_minimum_to_decode`` walks layers and
        returns EIO likewise; a 157-trial fuzz found the old any-k
        fallback claiming patterns decode_chunks then failed)."""
        if not (want_to_read - available):
            return set(want_to_read)
        # feas_have: what decode_chunks (given every available chunk)
        # would hold after each repair — drives feasibility, keeping
        # the claim in lockstep with the decode.  present: what a
        # replay holding ONLY the returned read set would hold — each
        # repair selects its inputs from chunks already present (prior
        # reads/repairs) before adding fresh available reads, so the
        # returned set is always a subset of ``available`` AND
        # sufficient on its own (the contract decode_object in
        # ec/stripe.py enforces).
        feas_have = set(available)
        present: set[int] = set()
        read: set[int] = set()
        progress = True
        while (want_to_read - feas_have) and progress:
            progress = False
            for layer in sorted(self.layers, key=lambda s: len(s.positions)):
                lost_here = [p for p in layer.positions if p not in feas_have]
                have_here = [p for p in layer.positions if p in feas_have]
                needed = len(layer.data_pos)
                if lost_here and len(have_here) >= needed:
                    # inputs already present (prior reads OR prior
                    # repairs) are free: only chunks appended by the
                    # fresh-available loop below cost a read.  A
                    # present-sourced chunk can be in ``available``
                    # without ever having been read (a prior layer
                    # repair regenerates ALL its positions), so
                    # filtering sel by ``available`` would claim
                    # redundant reads (round-4 ADVICE).
                    sel = [p for p in have_here if p in present][:needed]
                    for p in have_here:
                        if len(sel) >= needed:
                            break
                        if p not in sel and p in available:
                            sel.append(p)
                            read.add(p)
                    present |= set(sel) | set(layer.positions)
                    feas_have |= set(layer.positions)
                    progress = True
                    break
        if want_to_read - feas_have:
            raise ErasureCodeError(
                f"cannot decode chunks {sorted(want_to_read - feas_have)}"
            )
        return read | (want_to_read & available)

    def decode_chunks(
        self, want_to_read: set[int], chunks: dict[int, np.ndarray]
    ) -> dict[int, np.ndarray]:
        size = len(next(iter(chunks.values())))
        work = dict(chunks)
        erased = set(range(len(self.mapping))) - set(work)
        progress = True
        while erased & self._needed(want_to_read, erased) and progress:
            progress = False
            for layer in sorted(self.layers, key=lambda s: len(s.positions)):
                lost_here = [p for p in layer.positions if p in erased]
                have = [p for p in layer.positions if p in work]
                if lost_here and len(have) >= len(layer.data_pos):
                    layer.repair(work, erased, size)
                    progress = True
                    break
        still = [p for p in want_to_read if p not in work]
        if still:
            raise ErasureCodeError(f"cannot repair chunks {still}")
        return {p: work[p] for p in want_to_read}

    def _needed(self, want: set[int], erased: set[int]) -> set[int]:
        return want & erased

    def decode_concat(self, chunks: dict[int, np.ndarray]) -> bytes:
        dp = self._data_positions()
        chunk_size = len(next(iter(chunks.values())))
        decoded = self.decode(set(dp), chunks, chunk_size)
        return b"".join(decoded[p].tobytes() for p in dp)
