"""ISA-L-parity EC plugin.

Mirrors the reference's ``src/erasure-code/isa/ErasureCodeIsa{,TableCache}.{h,cc}``
surface: techniques ``reed_sol_van`` (default) and ``cauchy``, w = 8
only, 32-byte address alignment (``EC_ISA_ADDRESS_ALIGNMENT``), and an
instance-independent table cache keyed by (technique, k, m) — the
reference shares its precomputed ``ec_init_tables`` output across
plugin instances via ``ErasureCodeIsaTableCache``; here the cached
object is the compiled device codec, which serves the same purpose
(skip matrix/LUT/jit setup on repeat profiles).

The chunk mathematics is the same RS over GF(2^8) as jerasure's
``reed_sol_van`` — that is true upstream too (ISA-L is an alternate
CPU backend for identical codes, so encodings interoperate) — but the
plugin carries its own parsing, alignment and caching semantics
instead of aliasing the jerasure class.
"""

from __future__ import annotations

import threading

import numpy as np

from .. import gf
from ..backend import MatrixCodec
from ..interface import ErasureCode, ErasureCodeError, Profile

EC_ISA_ADDRESS_ALIGNMENT = 32
TECHNIQUES = ("reed_sol_van", "cauchy")


class _TableCache:
    """(technique, k, m) -> codec; the ErasureCodeIsaTableCache analog."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._codecs: dict[tuple[str, int, int], MatrixCodec] = {}

    def get(self, technique: str, k: int, m: int) -> MatrixCodec:
        key = (technique, k, m)
        with self._lock:
            codec = self._codecs.get(key)
            if codec is None:
                if technique == "cauchy":
                    matrix = gf.cauchy_good_matrix(k, m)
                else:
                    matrix = gf.vandermonde_matrix(k, m)
                codec = MatrixCodec(matrix, "table")
                self._codecs[key] = codec
            return codec


_CACHE = _TableCache()


class ErasureCodeIsa(ErasureCode):
    technique = "reed_sol_van"

    def init(self, profile: Profile) -> None:
        self.profile = profile
        self.k = profile.get_int("k", 7)      # reference DEFAULT_K
        self.m = profile.get_int("m", 3)      # reference DEFAULT_M
        self.technique = profile.get("technique", "reed_sol_van")
        if self.k < 1 or self.m < 1:
            raise ErasureCodeError(f"bad k={self.k} m={self.m}")
        if self.technique not in TECHNIQUES:
            raise ErasureCodeError(
                f"isa technique {self.technique!r} not in {TECHNIQUES}"
            )
        if self.k + self.m > 256:
            raise ErasureCodeError("isa: k+m > 2^8")
        self.w = 8
        self.codec = _CACHE.get(self.technique, self.k, self.m)

    def get_alignment(self) -> int:
        # reference: k * EC_ISA_ADDRESS_ALIGNMENT (ec_encode_data wants
        # 32-byte-aligned fragments)
        return self.k * EC_ISA_ADDRESS_ALIGNMENT

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        data = np.stack([chunks[i] for i in range(self.k)])
        coding = self.codec.encode(data)
        for i in range(self.m):
            chunks[self.k + i][:] = coding[i]

    def decode_chunks(
        self, want_to_read: set[int], chunks: dict[int, np.ndarray]
    ) -> dict[int, np.ndarray]:
        return self.codec.decode(dict(chunks), set(want_to_read))
