"""Shingled erasure code (SHEC).

Semantics per the reference's ``src/erasure-code/shec`` (Miyamae et
al., "SHEC"): SHEC(k, m, c) places m parities, each covering a
*shingle* — a circular window of ceil(k*c/m) consecutive data chunks
starting at floor(i*k/m) — so single-chunk recovery reads only a window
instead of k chunks, trading durability (not MDS) for recovery
efficiency.  ``c`` is the average parity coverage per data chunk.

Matrix construction matches the reference's
``ErasureCodeShec::shec_reedsolomon_coding_matrix`` at the default
w = 8: start from jerasure's systematized extended-Vandermonde coding
matrix (``reed_sol_vandermonde_coding_matrix(k, m, 8)`` — the same
construction the jerasure reed_sol_van plugin here is bit-exact
against), then zero every entry outside the row's shingle window, so
encoded parity bytes equal upstream's.  Because the code is not MDS,
decode solves the available linear system: identity rows for surviving
data + shingle rows for surviving parities, Gauss-eliminated on the
host to produce a reconstruction matrix, with the bulk multiply on
device (:class:`TableEncoder`).
"""

from __future__ import annotations

import math

import numpy as np

from .. import gf
from ..backend import TableEncoder
from ..interface import ErasureCode, ErasureCodeError, Profile


def _shingle_matrix(k: int, m: int, c: int) -> np.ndarray:
    """reed_sol Vandermonde coding matrix masked to the shingle pattern
    (reference shec_reedsolomon_coding_matrix, w=8)."""
    width = math.ceil(k * c / m)
    mat = gf.vandermonde_matrix(k, m)
    for i in range(m):
        start = (i * k) // m
        for j in range(k):
            if (j - start) % k >= width:
                mat[i, j] = 0
    return mat


class ErasureCodeShec(ErasureCode):
    def init(self, profile: Profile) -> None:
        self.profile = profile
        self.k = profile.get_int("k", 4)
        self.m = profile.get_int("m", 3)
        self.c = profile.get_int("c", 2)
        if not (0 < self.c <= self.m <= self.k):
            raise ErasureCodeError(
                f"need 0 < c={self.c} <= m={self.m} <= k={self.k}"
            )
        self.w = profile.get_int("w", 8)
        if self.w != 8:
            # upstream allows w in {8,16,32}; the GF(2^8) table engine
            # here covers the default — reject the rest loudly
            raise ErasureCodeError(
                f"w={self.w} not supported (only the upstream default "
                "w=8)"
            )
        self.matrix = _shingle_matrix(self.k, self.m, self.c)
        self.encoder = TableEncoder(self.matrix)
        self._solvers: dict[tuple, TableEncoder] = {}

    def get_alignment(self) -> int:
        return self.k * 8 * 4

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        data = np.stack([chunks[i] for i in range(self.k)])
        coding = self.encoder.encode(data)
        for i in range(self.m):
            chunks[self.k + i][:] = coding[i]

    # ---- recovery algebra ----

    def _system_rows(self, available: set[int]) -> tuple[np.ndarray, list[int]]:
        """Rows of the k-column GF system contributed by survivors."""
        rows = []
        ids = []
        for i in sorted(available):
            if i < self.k:
                r = np.zeros(self.k, np.uint8)
                r[i] = 1
            else:
                r = self.matrix[i - self.k]
            rows.append(r)
            ids.append(i)
        return np.array(rows, np.uint8), ids

    def _eliminated(self, available: tuple[int, ...]):
        """Row-reduce the survivor system, tracking combinations.

        Returns (a, t, pivots, ids): ``a`` the reduced rows, ``t`` the
        combination matrix (row i of ``a`` = t[i] @ original rows),
        ``pivots`` mapping column -> reduced row index.
        """
        rows, ids = self._system_rows(set(available))
        n = len(ids)
        a = rows.copy()
        t = np.eye(n, dtype=np.uint8)
        pivots: dict[int, int] = {}
        used = np.zeros(n, bool)
        for col in range(self.k):
            pr = next(
                (r for r in range(n) if not used[r] and a[r, col] != 0), None
            )
            if pr is None:
                continue  # free column: not determined by this subset
            used[pr] = True
            pivots[col] = pr
            f = gf.gf_inv(int(a[pr, col]))
            a[pr] = gf.mul_region(f, a[pr])
            t[pr] = gf.mul_region(f, t[pr])
            for r in range(n):
                if r != pr and a[r, col] != 0:
                    fr = int(a[r, col])
                    a[r] ^= gf.mul_region(fr, a[pr])
                    t[r] ^= gf.mul_region(fr, t[pr])
        return a, t, pivots, ids

    def _target_row(self, i: int) -> np.ndarray:
        """Chunk i as a k-vector over the data chunks."""
        if i < self.k:
            r = np.zeros(self.k, np.uint8)
            r[i] = 1
            return r
        return self.matrix[i - self.k].copy()

    def _express(self, elim, targets: list[int]) -> np.ndarray | None:
        """Coefficients expressing each target chunk from survivors,
        or None if any target is outside the row space."""
        a, t, pivots, ids = elim
        out = np.zeros((len(targets), len(ids)), np.uint8)
        for row_i, tgt in enumerate(targets):
            v = self._target_row(tgt)
            comb = np.zeros(len(ids), np.uint8)
            for col, pr in pivots.items():
                f = int(v[col])
                if f:
                    v ^= gf.mul_region(f, a[pr])
                    comb ^= gf.mul_region(f, t[pr])
            if v.any():
                return None
            out[row_i] = comb
        return out

    def _touching_rows(self, chunk: int) -> list[int]:
        """Parity rows whose shingle involves this chunk."""
        if chunk >= self.k:
            return [chunk - self.k]
        return [i for i in range(self.m) if self.matrix[i, chunk]]

    def _candidate_pool(self, erased: set[int], available: set[int]) -> set[int]:
        """Survivors plausibly useful for repairing ``erased``: members
        of every shingle window that (transitively, through other
        erased chunks) touches an erasure.  Bounds the search the way
        the reference does, instead of scanning all survivor subsets."""
        relevant = set(erased)
        while True:
            rows = {i for e in relevant for i in self._touching_rows(e)}
            members = {self.k + i for i in rows} | {
                j
                for i in rows
                for j in range(self.k)
                if self.matrix[i, j]
            }
            grown = relevant | (members & erased)
            if grown == relevant:
                return (members - erased) & available
            relevant = grown

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> set[int]:
        erased = want_to_read - available
        if not erased:
            return set(want_to_read)
        import itertools

        pool = sorted(self._candidate_pool(erased, available))
        if len(pool) <= 12:  # exact minimal search on the window pool
            for r in range(1, len(pool) + 1):
                for sub in itertools.combinations(pool, r):
                    if self._can_recover(set(sub), erased):
                        return set(sub) | (want_to_read & available)
        # greedy shrink (polynomial): start wide, drop what isn't needed
        for base in (set(pool), set(available)):
            if self._can_recover(base, erased):
                keep = set(base)
                for c in sorted(base):
                    if self._can_recover(keep - {c}, erased):
                        keep.discard(c)
                return keep | (want_to_read & available)
        raise ErasureCodeError(f"cannot recover {sorted(erased)}")

    def _can_recover(self, subset: set[int], erased: set[int]) -> bool:
        """Do these survivors determine the erased chunks?"""
        elim = self._eliminated(tuple(sorted(subset)))
        return self._express(elim, sorted(erased)) is not None

    def decode_chunks(
        self, want_to_read: set[int], chunks: dict[int, np.ndarray]
    ) -> dict[int, np.ndarray]:
        available = tuple(sorted(chunks))
        targets = sorted(want_to_read)
        key = (available, tuple(targets))
        if key not in self._solvers:
            elim = self._eliminated(available)
            recon = self._express(elim, targets)
            if recon is None:
                raise ErasureCodeError(
                    f"cannot decode {targets} from chunks {sorted(chunks)}"
                )
            self._solvers[key] = TableEncoder(recon)
        ids = sorted(available)
        survivors = np.stack([chunks[i] for i in ids])
        decoded = self._solvers[key].encode(survivors)
        return {
            tgt: np.ascontiguousarray(decoded[i])
            for i, tgt in enumerate(targets)
        }
