"""Coupled-layer (CLAY) MSR regenerating code.

Parity with the reference's ``src/erasure-code/clay/ErasureCodeClay.{h,cc}``
(the FAST'18 "Clay codes" construction): wraps a base MDS code
(scalar_mds, default jerasure reed_sol_van) and couples q*t node layers
pairwise so that single-node repair reads only ``q^{t-1}`` of the
``q^t`` sub-chunks from each of d helpers — repair-bandwidth optimal —
while any <= m erasures remain decodable.

Construction (q = d-k+1, t = (k+m+nu)/q with nu virtual zero chunks
for shortening; sub_chunk_count = q^t):

- nodes live on a q x t grid: chunk i -> (x = i % q, y = i // q);
- sub-chunks are indexed by planes z in [0,q)^t;
- the *uncoupled* symbols U(x,y;z) form, per plane, a codeword of the
  base (q*t - m, m) MDS code;
- the *coupled* (stored) symbols C relate pairwise: for x != z_y,
  with partner node (z_y, y) at partner plane z(y->x),

      C(x,y;z) = U(x,y;z) + g * U(z_y, y; z(y->x))

  (g = alpha, char-2 field, pair matrix [[1,g],[g,1]] invertible since
  det = 1 + g^2 != 0); on the diagonal (x == z_y) C = U.

Decode (and encode, which is just decode with the parity nodes
erased — the reference does the same via ``decode_layered``): process
planes by increasing *intersection score* (count of y whose dot node
(z_y, y) is erased); compute U at surviving nodes (partner known:
2x2 inverse; partner erased: partner plane has lower score and is
already fully U-decoded), then MDS-decode each plane's <= m unknown U
symbols; finally map U back to C at the erased nodes.

Single-node repair reads only planes with z_{y0} = x0 and is
implemented for the default d = k+m-1 (all surviving real nodes are
helpers), matching the reference's default profile.
"""

from __future__ import annotations

import numpy as np

from .. import gf
from ..backend import MatrixCodec
from ..interface import ErasureCode, ErasureCodeError, Profile

GAMMA = 2  # alpha; any g not in {0, 1} works (det 1 + g^2 != 0)


class ErasureCodeClay(ErasureCode):
    def init(self, profile: Profile) -> None:
        self.profile = profile
        self.k = profile.get_int("k", 4)
        self.m = profile.get_int("m", 2)
        self.d = profile.get_int("d", self.k + self.m - 1)
        if self.d != self.k + self.m - 1:
            raise ErasureCodeError(
                "only d = k+m-1 (the reference default) is supported"
            )
        self.q = self.d - self.k + 1  # == m
        km = self.k + self.m
        self.nu = (self.q - km % self.q) % self.q  # virtual chunks
        self.t = (km + self.nu) // self.q
        self.n = km + self.nu  # grid nodes (incl. virtual)
        self.sub_chunk_no = self.q**self.t
        scalar = profile.get("scalar_mds", "jerasure")
        technique = profile.get("technique", "reed_sol_van")
        if scalar not in ("jerasure", "isa", "jax"):
            raise ErasureCodeError(f"unknown scalar_mds {scalar!r}")
        # base MDS code over all grid nodes: (n - m) data, m parity
        if technique == "reed_sol_van":
            base = gf.vandermonde_matrix(self.n - self.m, self.m)
        elif technique == "cauchy_good":
            base = gf.cauchy_good_matrix(self.n - self.m, self.m)
        else:
            raise ErasureCodeError(f"unknown technique {technique!r}")
        self.base = MatrixCodec(base, "table")
        self._ginv = gf.gf_inv(GAMMA)
        self._det_inv = gf.gf_inv(1 ^ gf.gf_mul(GAMMA, GAMMA))

    # ---- geometry ----

    def _xy(self, i: int) -> tuple[int, int]:
        return i % self.q, i // self.q

    def _node(self, x: int, y: int) -> int:
        return y * self.q + x

    def _digit(self, z: int, y: int) -> int:
        return (z // self.q ** (self.t - 1 - y)) % self.q

    def _replace(self, z: int, y: int, x: int) -> int:
        p = self.q ** (self.t - 1 - y)
        return z + (x - self._digit(z, y)) * p

    def _base_id(self, node: int) -> int:
        """Grid node -> base-code symbol id (data 0..n-m-1, parity after).

        Real data and virtual nodes are base data; real parity chunks
        k..k+m-1 are the base parity symbols.
        """
        if node < self.k:
            return node
        if node >= self.k + self.m:  # virtual
            return self.k + (node - self.k - self.m)
        return (self.n - self.m) + (node - self.k)

    # ---- interface ----

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_sub_chunk_count(self) -> int:
        return self.sub_chunk_no

    def get_alignment(self) -> int:
        return self.k * self.sub_chunk_no * 8

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        size = len(chunks[0])
        if size % self.sub_chunk_no:
            raise ErasureCodeError(
                f"chunk size {size} not divisible by q^t={self.sub_chunk_no}"
            )
        erased = set(range(self.k, self.k + self.m))
        C = self._layout(chunks, size)
        self._decode_layered(C, erased, size // self.sub_chunk_no)
        for i in range(self.k, self.k + self.m):
            chunks[i][:] = C[i].reshape(-1)

    def decode_chunks(
        self, want_to_read: set[int], chunks: dict[int, np.ndarray]
    ) -> dict[int, np.ndarray]:
        size = len(next(iter(chunks.values())))
        erased = set(range(self.k + self.m)) - set(chunks)
        if len(erased) > self.m:
            raise ErasureCodeError(f"too many erasures: {sorted(erased)}")
        C = self._layout(chunks, size)
        self._decode_layered(C, erased, size // self.sub_chunk_no)
        return {
            i: np.ascontiguousarray(C[i].reshape(-1)) for i in want_to_read
        }

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> set[int]:
        if want_to_read <= available:
            return set(want_to_read)
        erased = want_to_read - available
        if len(erased) == 1 and len(available) >= self.d:
            # repair-optimal single-node path: d helpers
            return set(sorted(available)[: self.d])
        return self._minimum_to_decode(want_to_read, available)

    def minimum_to_decode_subchunks(
        self, lost: int, available: set[int]
    ) -> tuple[set[int], list[int]]:
        """Helpers + the plane indices each must supply (the reference's
        sub-chunk-range form of minimum_to_decode)."""
        if len(available) < self.d:
            raise ErasureCodeError(f"need d={self.d} helpers")
        x0, y0 = self._xy(lost)
        planes = [
            z for z in range(self.sub_chunk_no) if self._digit(z, y0) == x0
        ]
        return set(sorted(available)[: self.d]), planes

    # ---- core machinery ----

    def _layout(self, chunks: dict[int, np.ndarray], size: int):
        """C[node] = [q^t, sub] array; erased nodes zero-filled."""
        sub = size // self.sub_chunk_no
        C = np.zeros((self.n, self.sub_chunk_no, sub), np.uint8)
        for i, buf in chunks.items():
            C[i] = np.asarray(buf, np.uint8).reshape(self.sub_chunk_no, sub)
        return C

    def _pair_invert(self, c1, c2):
        """(C1, C2) -> (U1, U2) through [[1,g],[g,1]]^-1."""
        g, di = GAMMA, self._det_inv
        mt = gf.mul_table()
        u1 = mt[di][c1 ^ mt[g][c2]]
        u2 = mt[di][c2 ^ mt[g][c1]]
        return u1, u2

    def _decode_layered(
        self, C: np.ndarray, erased: set[int], sub: int
    ) -> None:
        """Recover C at erased nodes in place (<= m erasures)."""
        q, t, n = self.q, self.t, self.n
        mt = gf.mul_table()
        U = np.zeros_like(C)
        have_u = np.zeros((n, self.sub_chunk_no), bool)

        def score(z: int) -> int:
            return sum(
                1
                for y in range(t)
                if self._node(self._digit(z, y), y) in erased
            )

        order = sorted(range(self.sub_chunk_no), key=score)
        for z in order:
            # 1) U at surviving nodes
            for node in range(n):
                if node in erased:
                    continue
                x, y = self._xy(node)
                zy = self._digit(z, y)
                if x == zy:
                    U[node, z] = C[node, z]
                    have_u[node, z] = True
                    continue
                partner = self._node(zy, y)
                zpair = self._replace(z, y, x)
                if partner not in erased:
                    u1, _ = self._pair_invert(C[node, z], C[partner, zpair])
                    U[node, z] = u1
                else:
                    # partner plane has lower score: its U is complete
                    assert have_u[partner, zpair]
                    U[node, z] = C[node, z] ^ mt[GAMMA][U[partner, zpair]]
                have_u[node, z] = True
            # 2) MDS-decode the plane's erased U symbols
            if erased:
                avail = {
                    self._base_id(node): U[node, z]
                    for node in range(n)
                    if node not in erased
                }
                want = {self._base_id(node) for node in erased}
                out = self.base.decode(avail, want)
                for node in erased:
                    U[node, z] = out[self._base_id(node)]
                    have_u[node, z] = True
        # 3) U -> C at erased nodes
        for node in erased:
            x, y = self._xy(node)
            for z in range(self.sub_chunk_no):
                zy = self._digit(z, y)
                if x == zy:
                    C[node, z] = U[node, z]
                else:
                    partner = self._node(zy, y)
                    zpair = self._replace(z, y, x)
                    C[node, z] = U[node, z] ^ mt[GAMMA][U[partner, zpair]]

    # ---- repair-optimal single-node recovery ----

    def repair(
        self,
        lost: int,
        helper_subchunks: dict[int, dict[int, np.ndarray]],
    ) -> np.ndarray:
        """Recover chunk ``lost`` from helpers supplying ONLY the repair
        planes (z_{y0} = x0): q^{t-1} sub-chunks each.

        ``helper_subchunks[i][z]`` = helper i's sub-chunk for plane z.
        Returns the full reconstructed chunk (q^t sub-chunks).
        """
        q, t, n = self.q, self.t, self.n
        mt = gf.mul_table()
        x0, y0 = self._xy(lost)
        planes = [
            z for z in range(self.sub_chunk_no) if self._digit(z, y0) == x0
        ]
        real = set(range(self.k + self.m))
        helpers = set(helper_subchunks)
        if helpers != real - {lost}:
            raise ErasureCodeError(
                "repair needs all surviving real chunks as helpers "
                f"(d = k+m-1); got {sorted(helpers)}"
            )
        sub = len(next(iter(helper_subchunks[next(iter(helpers))].values())))

        def cval(node: int, z: int) -> np.ndarray:
            if node >= self.k + self.m:  # virtual: zero
                return np.zeros(sub, np.uint8)
            return helper_subchunks[node][z]

        # U on the repair planes
        U = {}
        for z in planes:
            unknowns = set()
            for node in range(n):
                x, y = self._xy(node)
                if node == lost or (y == y0 and x != x0):
                    unknowns.add(node)
                    continue
                zy = self._digit(z, y)
                if x == zy:
                    U[(node, z)] = cval(node, z)
                else:
                    partner = self._node(zy, y)
                    zpair = self._replace(z, y, x)
                    # partner is never the lost node (y != y0 here) and
                    # zpair stays in the repair set (y0 digit unchanged)
                    u1, _ = self._pair_invert(cval(node, z), cval(partner, zpair))
                    U[(node, z)] = u1
            avail = {
                self._base_id(node): U[(node, z)]
                for node in range(n)
                if node not in unknowns
            }
            want = {self._base_id(node) for node in unknowns}
            out = self.base.decode(avail, want)
            for node in unknowns:
                U[(node, z)] = out[self._base_id(node)]

        # reconstruct the lost chunk
        out = np.zeros((self.sub_chunk_no, sub), np.uint8)
        for z in range(self.sub_chunk_no):
            zy0 = self._digit(z, y0)
            if zy0 == x0:
                out[z] = U[(lost, z)]
            else:
                xp = zy0  # partner column
                partner = self._node(xp, y0)
                zpair = self._replace(z, y0, x0)  # in the repair set
                # partner's pair equation at plane zpair reveals U(lost, z)
                u_lost = mt[self._ginv][
                    cval(partner, zpair) ^ U[(partner, zpair)]
                ]
                out[z] = u_lost ^ mt[GAMMA][U[(partner, zpair)]]
        return out.reshape(-1)
