"""Coupled-layer (CLAY) MSR regenerating code.

Parity with the reference's ``src/erasure-code/clay/ErasureCodeClay.{h,cc}``
(the FAST'18 "Clay codes" construction): wraps a base MDS code
(scalar_mds, default jerasure reed_sol_van) and couples q*t node layers
pairwise so that single-node repair reads only ``q^{t-1}`` of the
``q^t`` sub-chunks from each of d helpers — repair-bandwidth optimal —
while any <= m erasures remain decodable.

Construction (q = d-k+1, t = (k+m+nu)/q with nu virtual zero chunks
for shortening; sub_chunk_count = q^t):

- nodes live on a q x t grid: chunk i -> (x = i % q, y = i // q);
- sub-chunks are indexed by planes z in [0,q)^t;
- the *uncoupled* symbols U(x,y;z) form, per plane, a codeword of the
  base (q*t - m, m) MDS code;
- the *coupled* (stored) symbols C relate pairwise: for x != z_y,
  with partner node (z_y, y) at partner plane z(y->x),

      C(x,y;z) = U(x,y;z) + g * U(z_y, y; z(y->x))

  (g = alpha, char-2 field, pair matrix [[1,g],[g,1]] invertible since
  det = 1 + g^2 != 0); on the diagonal (x == z_y) C = U.

Decode (and encode, which is just decode with the parity nodes
erased — the reference does the same via ``decode_layered``): process
planes by increasing *intersection score* (count of y whose dot node
(z_y, y) is erased); compute U at surviving nodes (partner known:
2x2 inverse; partner erased: partner plane has lower score and is
already fully U-decoded), then MDS-decode each plane's <= m unknown U
symbols; finally map U back to C at the erased nodes.

Single-node repair reads only planes with z_{y0} = x0, for any
k <= d <= k+m-1 (upstream ErasureCodeClay::parse bounds).  At the
default d = k+m-1 every surviving real node helps; for smaller d the
k+m-1-d aloof survivors are carried as extra MDS erasures and repair
planes are processed by aloof-intersection score, mirroring upstream
repair_one_lost_chunk's order classes.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .. import gf
from ..backend import MatrixCodec
from ..interface import ErasureCode, ErasureCodeError, Profile

GAMMA = 2  # alpha; any g not in {0, 1} works (det 1 + g^2 != 0)


def _gf_lut(table_np: np.ndarray, x):
    """``table[x]`` inside the device kernels: the Pallas byte-table
    kernel on the chip (XLA per-lane gathers run ~10 ns/lane there —
    round-3 silicon profiling), plain jnp gather elsewhere.  The table
    is a host constant (a mul_table row)."""
    if jax.default_backend() == "tpu":
        from ..pallas_gf import byte_lut

        return byte_lut(x, table_np, interpret=False)
    return jnp.take(jnp.asarray(table_np), jnp.asarray(x).astype(jnp.int32))


class ErasureCodeClay(ErasureCode):
    def init(self, profile: Profile) -> None:
        self.profile = profile
        self.k = profile.get_int("k", 4)
        self.m = profile.get_int("m", 2)
        self.d = profile.get_int("d", self.k + self.m - 1)
        if not self.k <= self.d <= self.k + self.m - 1:
            raise ErasureCodeError(
                f"d={self.d} must satisfy k <= d <= k+m-1 "
                f"(k={self.k}, m={self.m}; upstream ErasureCodeClay::parse)"
            )
        self.q = self.d - self.k + 1  # == m only at the default d
        km = self.k + self.m
        self.nu = (self.q - km % self.q) % self.q  # virtual chunks
        self.t = (km + self.nu) // self.q
        self.n = km + self.nu  # grid nodes (incl. virtual)
        self.sub_chunk_no = self.q**self.t
        scalar = profile.get("scalar_mds", "jerasure")
        technique = profile.get("technique", "reed_sol_van")
        if scalar not in ("jerasure", "isa", "jax"):
            raise ErasureCodeError(f"unknown scalar_mds {scalar!r}")
        # base MDS code over all grid nodes: (n - m) data, m parity
        if technique == "reed_sol_van":
            base = gf.vandermonde_matrix(self.n - self.m, self.m)
        elif technique == "cauchy_good":
            base = gf.cauchy_good_matrix(self.n - self.m, self.m)
        else:
            raise ErasureCodeError(f"unknown technique {technique!r}")
        self.base = MatrixCodec(base, "table")
        self._ginv = gf.gf_inv(GAMMA)
        self._det_inv = gf.gf_inv(1 ^ gf.gf_mul(GAMMA, GAMMA))

    # ---- geometry ----

    def _xy(self, i: int) -> tuple[int, int]:
        return i % self.q, i // self.q

    def _node(self, x: int, y: int) -> int:
        return y * self.q + x

    def _digit(self, z: int, y: int) -> int:
        return (z // self.q ** (self.t - 1 - y)) % self.q

    def _replace(self, z: int, y: int, x: int) -> int:
        p = self.q ** (self.t - 1 - y)
        return z + (x - self._digit(z, y)) * p

    def _base_id(self, node: int) -> int:
        """Grid node -> base-code symbol id (data 0..n-m-1, parity after).

        Real data and virtual nodes are base data; real parity chunks
        k..k+m-1 are the base parity symbols.
        """
        if node < self.k:
            return node
        if node >= self.k + self.m:  # virtual
            return self.k + (node - self.k - self.m)
        return (self.n - self.m) + (node - self.k)

    # ---- interface ----

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_sub_chunk_count(self) -> int:
        return self.sub_chunk_no

    def get_alignment(self) -> int:
        return self.k * self.sub_chunk_no * 8

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        size = len(chunks[0])
        if size % self.sub_chunk_no:
            raise ErasureCodeError(
                f"chunk size {size} not divisible by q^t={self.sub_chunk_no}"
            )
        erased = set(range(self.k, self.k + self.m))
        C = self._layout(chunks, size)
        self._decode_layered(C, erased, size // self.sub_chunk_no)
        for i in range(self.k, self.k + self.m):
            chunks[i][:] = C[i].reshape(-1)

    def decode_chunks(
        self, want_to_read: set[int], chunks: dict[int, np.ndarray]
    ) -> dict[int, np.ndarray]:
        size = len(next(iter(chunks.values())))
        erased = set(range(self.k + self.m)) - set(chunks)
        if len(erased) > self.m:
            raise ErasureCodeError(f"too many erasures: {sorted(erased)}")
        C = self._layout(chunks, size)
        self._decode_layered(C, erased, size // self.sub_chunk_no)
        return {
            i: np.ascontiguousarray(C[i].reshape(-1)) for i in want_to_read
        }

    def _repair_helpers(self, lost: int, available: set[int]) -> set[int] | None:
        """Pick the d helper chunks for single-node repair, or None if
        the repair-optimal path is not possible.

        Every surviving real node in the lost node's grid row must help:
        their stored repair-plane bytes appear irreplaceably in the
        rebuild pair equations (upstream is_repair refuses otherwise and
        falls back to conventional decode).  The rest are filled in node
        order, as upstream minimum_to_repair does.
        """
        if len(available) < self.d:
            return None
        x0, y0 = self._xy(lost)
        real = set(range(self.k + self.m))
        row = ({self._node(x, y0) for x in range(self.q)} & real) - {lost}
        if not row <= available:
            return None
        helpers = set(row)
        for c in sorted(available):
            if len(helpers) == self.d:
                break
            helpers.add(c)
        return helpers if len(helpers) == self.d else None

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> set[int]:
        if want_to_read <= available:
            return set(want_to_read)
        erased = want_to_read - available
        if len(erased) == 1 and len(want_to_read) == 1:
            # repair-optimal single-node path: d helpers.  Upstream
            # is_repair also requires a single *wanted* chunk — with
            # d < k+m-1 the helper set may exclude other wanted chunks,
            # so multi-chunk wants take the conventional minimum.
            helpers = self._repair_helpers(next(iter(erased)), available)
            if helpers is not None:
                return helpers
        return self._minimum_to_decode(want_to_read, available)

    def minimum_to_decode_subchunks(
        self, lost: int, available: set[int]
    ) -> tuple[set[int], list[int]]:
        """Helpers + the plane indices each must supply (the reference's
        sub-chunk-range form of minimum_to_decode)."""
        helpers = self._repair_helpers(lost, available)
        if helpers is None:
            raise ErasureCodeError(
                f"no repair-optimal helper set for {lost} in "
                f"{sorted(available)} (need d={self.d} incl. the lost row)"
            )
        x0, y0 = self._xy(lost)
        planes = [
            z for z in range(self.sub_chunk_no) if self._digit(z, y0) == x0
        ]
        return helpers, planes

    # ---- core machinery ----

    def _layout(self, chunks: dict[int, np.ndarray], size: int):
        """C[node] = [q^t, sub] array; erased nodes zero-filled."""
        sub = size // self.sub_chunk_no
        C = np.zeros((self.n, self.sub_chunk_no, sub), np.uint8)
        for i, buf in chunks.items():
            C[i] = np.asarray(buf, np.uint8).reshape(self.sub_chunk_no, sub)
        return C

    def _geometry(self):
        """Vectorized plane geometry, computed once per codec instance.

        Returns (digits [Z,t], x [n], y [n], partner [n,Z], zpair [n,Z],
        diag [n,Z], pw [t]) where partner/zpair/diag encode, for every
        (node, plane), the coupled-pair structure the scalar reference
        walks one plane at a time.
        """
        if not hasattr(self, "_geom"):
            q, t, n, Z = self.q, self.t, self.n, self.sub_chunk_no
            pw = q ** (t - 1 - np.arange(t))  # [t]
            z = np.arange(Z)
            digits = (z[:, None] // pw[None, :]) % q  # [Z, t]
            x = np.arange(n) % q
            y = np.arange(n) // q
            zy = digits[:, y].T  # [n, Z] — the node-row digit per plane
            partner = y[:, None] * q + zy  # [n, Z]
            zpair = z[None, :] + (x[:, None] - zy) * pw[y][:, None]  # [n, Z]
            diag = zy == x[:, None]  # [n, Z]
            self._geom = (digits, x, y, partner, zpair, diag, pw)
        return self._geom

    def _pair_invert(self, c1, c2):
        """(C1, C2) -> (U1, U2) through [[1,g],[g,1]]^-1 (vectorized)."""
        g, di = GAMMA, self._det_inv
        mt = gf.mul_table()
        u1 = mt[di][c1 ^ mt[g][c2]]
        u2 = mt[di][c2 ^ mt[g][c1]]
        return u1, u2

    def _decode_layered(
        self, C: np.ndarray, erased: set[int], sub: int
    ) -> None:
        """Recover C at erased nodes in place (<= m erasures).

        Planes are processed in batches by *intersection score*: a
        plane's erased-partner lookups only ever reference planes of
        strictly lower score, so all planes of one score class are
        independent — per class the engine runs exactly two device
        steps (one jitted pair-transform over every surviving node at
        once, one batched MDS solve over the class's plane stripe),
        versus the reference's per-plane-per-node scalar loops
        (``ErasureCodeClay.cc :: decode_layered``).  All index arrays
        are trace-time constants (cached per erased set, like the
        repair kernels), so the gathers compile to static reshuffles.
        """
        known_fns, rebuild_fn, classes = self._decode_kernels(
            frozenset(erased)
        )
        U = np.zeros_like(C)
        er = np.zeros(self.n, bool)
        er[list(erased)] = True
        known = np.nonzero(~er)[0]
        C_dev = jnp.asarray(C)  # C is read-only until step 3: upload once
        for (P, fn) in zip(classes, known_fns):
            # 1) U at surviving nodes for the whole class: one device op
            # per score class — the next class's host-side MDS solve
            # reads this U, so the pull is a real data dependency, not
            # a stray sync (q classes total, not per-plane)
            U[np.ix_(known, P)] = np.asarray(fn(C_dev, jnp.asarray(U)))  # jaxlint: disable=J003
            # 2) one batched MDS solve for the whole class
            avail = {
                self._base_id(node): U[node, P].reshape(-1)
                for node in known
            }
            want = {self._base_id(node) for node in erased}
            out = self.base.decode(avail, want)
            for node in erased:
                U[node, P] = out[self._base_id(node)].reshape(len(P), sub)
        # 3) U -> C at erased nodes, all planes at once: one device op
        er_nodes = sorted(erased)
        C[er_nodes] = np.asarray(rebuild_fn(jnp.asarray(U)))

    def _decode_kernels(self, erased_key: frozenset):
        """Jitted device kernels for decode, cached per erased set:
        per-score-class U-at-known transforms + the final U->C rebuild."""
        if not hasattr(self, "_decode_fns"):
            self._decode_fns = {}
        if erased_key in self._decode_fns:
            return self._decode_fns[erased_key]
        n = self.n
        mt = gf.mul_table()
        digits, _x, _y, partner, zpair, diag, _pw = self._geometry()
        er = np.zeros(n, bool)
        er[list(erased_key)] = True
        node_ids = digits + (np.arange(self.t)[None, :] * self.q)
        score = er[node_ids].sum(axis=1)  # [Z]
        known = np.nonzero(~er)[0]
        tab_g = mt[GAMMA]
        tab_di = mt[self._det_inv]

        classes = []
        known_fns = []
        for s in sorted(set(score.tolist())):
            P = np.nonzero(score == s)[0]
            classes.append(P)
            kn = known[:, None]  # [K, 1]
            d_mask = jnp.asarray(diag[kn, P[None, :]][..., None])
            pa = jnp.asarray(partner[kn, P[None, :]])  # [K, P]
            zp = jnp.asarray(zpair[kn, P[None, :]])
            pe = jnp.asarray(er[partner[kn, P[None, :]]][..., None])
            kn_j = jnp.asarray(known)
            P_j = jnp.asarray(P)

            def fn(C_j, U_j, *, d_mask=d_mask, pa=pa, zp=zp, pe=pe,
                   kn_j=kn_j, P_j=P_j):
                cn = C_j[kn_j[:, None], P_j[None, :]]  # [K, P, sub]
                cpart = C_j[pa, zp]
                upa = U_j[pa, zp]
                u_pair = _gf_lut(tab_di, cn ^ _gf_lut(tab_g, cpart))
                u_pe = cn ^ _gf_lut(tab_g, upa)
                return jnp.where(d_mask, cn, jnp.where(pe, u_pe, u_pair))

            # one wrapper per score class, built once and memoized per
            # erasure pattern (self._decode_fns / _repair_fns) — not a
            # per-iteration recompile
            known_fns.append(jax.jit(fn))  # jaxlint: disable=J004

        er_nodes = np.array(sorted(erased_key), np.int32)
        d_e = jnp.asarray(diag[er_nodes][..., None])
        pa_e = jnp.asarray(partner[er_nodes])  # [E, Z]
        zp_e = jnp.asarray(zpair[er_nodes])
        er_j = jnp.asarray(er_nodes)

        @jax.jit
        def rebuild_fn(U_j):
            ue = U_j[er_j]  # [E, Z, sub]
            upz = U_j[pa_e, zp_e]
            return jnp.where(d_e, ue, ue ^ _gf_lut(tab_g, upz))

        self._decode_fns[erased_key] = (known_fns, rebuild_fn, classes)
        return self._decode_fns[erased_key]

    # ---- repair-optimal single-node recovery ----

    def repair(
        self,
        lost: int,
        helper_subchunks: dict[int, dict[int, np.ndarray]],
    ) -> np.ndarray:
        """Recover chunk ``lost`` from d helpers supplying ONLY the
        repair planes (z_{y0} = x0): q^{t-1} sub-chunks each.

        ``helper_subchunks[i][z]`` = helper i's sub-chunk for plane z.
        Returns the full reconstructed chunk (q^t sub-chunks).

        With d < k+m-1 the k+m-1-d non-helping survivors ("aloof"
        nodes, upstream repair_one_lost_chunk) are treated as erasures:
        repair planes are processed in classes of increasing aloof
        intersection score, exactly like _decode_layered, and each
        class's MDS solve carries m unknowns (the q-node lost row plus
        the aloof nodes).
        """
        n = self.n
        x0, y0 = self._xy(lost)
        digits, xv, yv, _partner, _zpair, _diag, _pw = self._geometry()
        planes = np.nonzero(digits[:, y0] == x0)[0]  # [P] repair planes
        npl = len(planes)
        real = set(range(self.k + self.m))
        helpers = set(helper_subchunks)
        if helpers != self._repair_helpers(lost, helpers):
            raise ErasureCodeError(
                f"repair of {lost} needs d={self.d} helpers including "
                f"every survivor in its grid row; got {sorted(helpers)}"
            )
        aloof = real - helpers - {lost}
        sub = len(next(iter(helper_subchunks[next(iter(helpers))].values())))

        # helper sub-chunks on the repair planes; virtual nodes are zero
        Cp = np.zeros((n, npl, sub), np.uint8)
        for i in helpers:
            Cp[i] = np.stack([helper_subchunks[i][int(z)] for z in planes])

        # unknown nodes: the whole grid row y0 (incl. virtual columns)
        # plus the aloof survivors — m base symbols per plane
        unknown = np.zeros(n, bool)
        unknown[lost] = True
        unknown[(yv == y0) & (xv != x0)] = True
        unknown[list(aloof)] = True
        known = np.nonzero(~unknown)[0]

        known_fns, classes, rebuild_fn = self._repair_kernels(
            lost, frozenset(aloof)
        )

        U = np.zeros((n, npl, sub), np.uint8)
        Cp_dev = jnp.asarray(Cp)
        for P_pos, fn in zip(classes, known_fns):
            # U at known nodes for this score class: one device op.  A
            # known node's partner shares its row (y != y0), so the pair
            # plane keeps the y0 digit and stays in the repair set; an
            # aloof partner's U comes from a strictly lower class — the
            # per-class pull is that sequential dependency, not a stray
            # sync
            # the plane count is a pure function of the (lost, aloof)
            # cache key, and the kernels are cached per key, so every
            # cached program sees one fixed shape — no unbounded
            # recompile despite the data-dependent count
            U[np.ix_(known, P_pos)] = np.asarray(fn(Cp_dev, jnp.asarray(U)))  # jaxlint: disable=J003,J013
            # batched MDS solve for the class's plane stripe
            avail = {
                self._base_id(node): U[node][P_pos].reshape(-1)
                for node in known
            }
            want = {self._base_id(node) for node in np.nonzero(unknown)[0]}
            solved = self.base.decode(avail, want)
            for node in np.nonzero(unknown)[0]:
                U[node][P_pos] = solved[self._base_id(node)].reshape(
                    len(P_pos), sub
                )

        # reconstruct the lost chunk over the full plane space (device);
        # same per-(lost, aloof)-key shape stability as the class loop
        out = np.asarray(rebuild_fn(Cp_dev, jnp.asarray(U)))  # jaxlint: disable=J013
        return np.ascontiguousarray(out.reshape(-1))

    def _repair_kernels(self, lost: int, aloof_key: frozenset):
        """Jitted device kernels for the repair hot path, cached per
        (lost node, aloof set): per-score-class U-at-known transforms
        (plane positions indexed into the repair stripe) + the final
        lost-chunk rebuild [Z,sub] <- (Cp, U)."""
        if not hasattr(self, "_repair_fns"):
            self._repair_fns = {}
        key = (lost, aloof_key)
        if key in self._repair_fns:
            return self._repair_fns[key]
        n, Z = self.n, self.sub_chunk_no
        mt = gf.mul_table()
        x0, y0 = self._xy(lost)
        digits, xv, yv, partner, zpair, diag, pw = self._geometry()
        planes = np.nonzero(digits[:, y0] == x0)[0]
        pos = np.full(Z, -1)
        pos[planes] = np.arange(len(planes))
        unknown = np.zeros(n, bool)
        unknown[lost] = True
        unknown[(yv == y0) & (xv != x0)] = True
        unknown[list(aloof_key)] = True
        known = np.nonzero(~unknown)[0]

        tab_g = mt[GAMMA]
        tab_di = mt[self._det_inv]
        tab_gi = mt[self._ginv]

        # score: per repair plane, how many rows' plane-digit selects an
        # aloof node (row y0 is never aloof: its survivors must help)
        aloof_mask = np.zeros(n, bool)
        aloof_mask[list(aloof_key)] = True
        node_ids = digits + (np.arange(self.t)[None, :] * self.q)  # [Z, t]
        score = aloof_mask[node_ids].sum(axis=1)[planes]  # [P]

        classes = []
        known_fns = []
        for s in sorted(set(score.tolist())):
            P_pos = np.nonzero(score == s)[0]  # positions in the stripe
            classes.append(P_pos)
            zsel = planes[P_pos]  # absolute plane ids
            kn = known[:, None]  # [K, 1]
            d_mask = jnp.asarray(diag[kn, zsel[None, :]][..., None])
            pa = jnp.asarray(partner[kn, zsel[None, :]])  # [K, P]
            pz = jnp.asarray(pos[zpair[kn, zsel[None, :]]])
            pe = jnp.asarray(
                aloof_mask[partner[kn, zsel[None, :]]][..., None]
            )
            known_j = jnp.asarray(known)

            def fn(Cp, U, *, d_mask=d_mask, pa=pa, pz=pz, pe=pe,
                   known_j=known_j, P_j=jnp.asarray(P_pos)):
                cn = Cp[known_j[:, None], P_j[None, :]]  # [K, P, sub]
                cpart = Cp[pa, pz]
                upa = U[pa, pz]
                u_pair = _gf_lut(tab_di, cn ^ _gf_lut(tab_g, cpart))
                u_pe = cn ^ _gf_lut(tab_g, upa)
                return jnp.where(d_mask, cn, jnp.where(pe, u_pe, u_pair))

            # one wrapper per score class, built once and memoized per
            # erasure pattern (self._decode_fns / _repair_fns) — not a
            # per-iteration recompile
            known_fns.append(jax.jit(fn))  # jaxlint: disable=J004

        zy0 = digits[:, y0]
        partner0 = jnp.asarray(y0 * self.q + zy0)
        pidx = jnp.asarray(pos[np.arange(Z) + (x0 - zy0) * pw[y0]])
        on_diag_idx = jnp.asarray(np.maximum(pos, 0))
        diag_mask = jnp.asarray((zy0 == x0)[:, None])

        @jax.jit
        def rebuild_fn(Cp, U):
            u_pz = U[partner0, pidx]  # [Z, sub]
            c_pz = Cp[partner0, pidx]
            # partner's pair equation at plane zpair reveals U(lost, z)
            u_lost = _gf_lut(tab_gi, c_pz ^ u_pz)
            off_diag = u_lost ^ _gf_lut(tab_g, u_pz)
            on_diag = U[lost, on_diag_idx]
            return jnp.where(diag_mask, on_diag, off_diag)

        self._repair_fns[key] = (known_fns, classes, rebuild_fn)
        return self._repair_fns[key]
