"""Device (TPU) erasure-coding engines.

Two execution strategies behind the plugins (SURVEY.md §2.2, §7 M4):

- :class:`TableEncoder` — GF(2^8) matrix multiply via 256-entry
  log/antilog-derived lookup rows (``jnp.take`` gathers + XOR
  accumulate).  General: works for any coding matrix; the correctness
  anchor.  (Replaces the reference's ``galois_w08_region_multiply``
  SIMD loops, upstream bundled gf-complete.)

- :class:`BitmatrixEncoder` — the MXU play: the GF(2^w) matrix is
  expanded once (host) to an (m*8) x (k*8) GF(2) bit-matrix
  (``jerasure_matrix_to_bitmatrix`` semantics); data bytes are
  bit-sliced and parity is one int8 matmul on the systolic array
  followed by ``& 1`` and bit re-pack.  GF(2) dot = AND + XOR =
  (integer matmul) mod 2.

Both are bit-exact against the host references in
:mod:`ceph_tpu.ec.gf` / ``cpp/gf_ref.cpp``.

Decode strategy (both): select k surviving generator rows, invert on
host (tiny k x k / 8k x 8k, exact integer math), then run the same bulk
device multiply — mirroring the reference's
``jerasure_matrix_decode`` structure.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import gf

W = 8


class TableEncoder:
    """GF(2^8) matrix x data on device via per-coefficient LUTs.

    On the chip the lookups run through the fused Pallas byte-table
    kernel (:func:`ceph_tpu.ec.pallas_gf.matrix_encode`) — XLA's
    per-lane gathers cost ~10 ns/lane there (round-3 silicon
    profiling); elsewhere the jnp gather path is used.  Both are
    bit-identical (tests/test_pallas_gf.py)."""

    def __init__(self, matrix: np.ndarray):
        self.matrix = np.asarray(matrix, np.uint8)
        self.m, self.k = self.matrix.shape
        # rows of the full product table for each coefficient: [m, k, 256]
        self.luts = gf.mul_table()[self.matrix]
        m, k = self.m, self.k
        luts_np = self.luts
        matrix_np = self.matrix

        # per-instance jit (not a static-self method): the compiled
        # executable's lifetime is tied to this encoder, so dropped
        # encoders don't pin cache entries forever
        def _encode(data: jnp.ndarray) -> jnp.ndarray:
            if jax.default_backend() == "tpu":
                from .pallas_gf import matrix_encode

                return matrix_encode(matrix_np, data, interpret=False)
            luts = jnp.asarray(luts_np)
            idx = data.astype(jnp.int32)  # [k, S]

            def row(i):
                acc = jnp.zeros(data.shape[1], jnp.uint8)
                for j in range(k):
                    acc = acc ^ jnp.take(luts[i, j], idx[j], axis=0)
                return acc

            return jnp.stack([row(i) for i in range(m)])

        self._encode = jax.jit(_encode)

    def encode(self, data: np.ndarray) -> np.ndarray:
        """data [k, S] u8 -> coding [m, S] u8."""
        return np.asarray(self.encode_async(data))

    def encode_async(self, data) -> jnp.ndarray:
        """Dispatch the encode without a host sync; the caller
        materializes with ``np.asarray`` when it needs the bytes.

        Lets the recovery executor co-schedule several small pattern
        groups: a committed input (``jax.device_put`` onto a chosen
        device) pins where the launch runs, so back-to-back dispatches
        round-robined over a mesh's local devices genuinely overlap.
        """
        return self._encode(jnp.asarray(data))


class BitmatrixEncoder:
    """GF(2) bit-matrix x bit-sliced data as an int8 MXU matmul.

    Packet layout matches the host/CPU reference
    (``gfref_bitmatrix_encode``): each chunk is groups of ``w`` packets
    of ``packetsize`` bytes; row (i*w+t) of the bit-matrix XORs data
    packets (j*w+l).  The bit-slicing of *bytes* (always 8 lanes) is
    independent of the code's ``w``; bits within bytes are untouched
    SIMD lanes, so unpack/pack order only needs to be self-consistent.
    """

    def __init__(self, bitmatrix: np.ndarray, packetsize: int, w: int = W):
        self.bitmatrix = np.asarray(bitmatrix, np.uint8)
        self.mw, self.kw = self.bitmatrix.shape
        self.w = w
        self.k, self.m = self.kw // w, self.mw // w
        self.packetsize = packetsize
        self._encode = jax.jit(self._encode_impl)

    def _encode_impl(self, data: jnp.ndarray) -> jnp.ndarray:
        k, m, p, w = self.k, self.m, self.packetsize, self.w
        size = data.shape[1]
        g = size // (w * p)  # groups per chunk
        # [k, S] -> packet rows [k*w, g*p] indexed s = j*w + l
        d = data.reshape(k, g, w, p).transpose(0, 2, 1, 3).reshape(k * w, g * p)
        # bit-slice bytes -> [k*w, g*p*8] in {0,1}
        shifts = jnp.arange(W, dtype=jnp.uint8)
        bits = ((d[:, :, None] >> shifts) & 1).astype(jnp.int8)
        bits = bits.reshape(k * w, g * p * W)
        bm = jnp.asarray(self.bitmatrix, jnp.int8)  # [m*w, k*w]
        # the MXU contraction: [m*w, k*w] @ [k*w, N] -> int32, parity = &1
        cbits = jax.lax.dot_general(
            bm,
            bits,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        cbits = (cbits & 1).astype(jnp.uint8)
        # re-pack bits -> bytes
        cb = cbits.reshape(m * w, g * p, W)
        weights = (jnp.uint8(1) << shifts).astype(jnp.uint8)
        packed = jnp.sum(cb * weights, axis=-1, dtype=jnp.uint8)
        # packet rows -> [m, S]
        return (
            packed.reshape(m, w, g, p).transpose(0, 2, 1, 3).reshape(m, size)
        )

    def encode(self, data: np.ndarray) -> np.ndarray:
        size = data.shape[1]
        group = self.w * self.packetsize
        if size % group:
            raise ValueError(
                f"chunk size {size} not a multiple of w*packetsize={group}"
            )
        return np.asarray(self._encode(jnp.asarray(data)))


class _SystematicCodec:
    """Shared encode/decode driver for systematic [I; M] codes.

    Subclasses set ``self.encoder`` and implement ``_build_decoder``
    (the reconstruction program for a given surviving-row set); the
    decode flow — pick k survivors, cache the decoder, regenerate any
    wanted coding chunks — is identical for the GF(2^8) matrix and the
    GF(2) bit-matrix representations.
    """

    k: int
    m: int
    encoder: TableEncoder | BitmatrixEncoder

    def __init__(self):
        self._decoders: dict[tuple, TableEncoder | BitmatrixEncoder] = {}

    def encode(self, data: np.ndarray) -> np.ndarray:
        return self.encoder.encode(data)

    def _build_decoder(self, rows: tuple[int, ...]):
        raise NotImplementedError

    def decode(
        self, available: dict[int, np.ndarray], want: set[int]
    ) -> dict[int, np.ndarray]:
        """Reconstruct wanted chunk ids (0..k-1 data, k..k+m-1 coding)."""
        have = set(available)
        if len(have) < self.k:
            raise ValueError("not enough chunks to decode")
        out: dict[int, np.ndarray] = {}
        missing_data = [i for i in range(self.k) if i not in have]
        if missing_data:
            rows = tuple(sorted(have)[: self.k])
            key = ("d", rows)
            if key not in self._decoders:
                self._decoders[key] = self._build_decoder(rows)
            survivors = np.stack([available[r] for r in rows])
            data = self._decoders[key].encode(survivors)
        else:
            data = np.stack([available[i] for i in range(self.k)])
        for i in range(self.k):
            if i in want:
                out[i] = np.ascontiguousarray(data[i])
        coding_want = [i for i in want if i >= self.k]
        if coding_want:
            coding = self.encode(data)
            for i in coding_want:
                out[i] = np.ascontiguousarray(coding[i - self.k])
        return out


class MatrixCodec(_SystematicCodec):
    """Encode/decode driver for a systematic [I; M] GF(2^8) code."""

    def __init__(self, matrix: np.ndarray, technique: str = "table",
                 packetsize: int = 64):
        super().__init__()
        self.matrix = np.asarray(matrix, np.uint8)
        self.m, self.k = self.matrix.shape
        self.technique = technique
        self.packetsize = packetsize
        if technique == "bitmatrix":
            self.bitmatrix = gf.matrix_to_bitmatrix(self.matrix)
            self.encoder = BitmatrixEncoder(self.bitmatrix, packetsize)
        else:
            self.encoder = TableEncoder(self.matrix)

    def generator(self) -> np.ndarray:
        """(k+m) x k generator with identity top block."""
        return np.vstack([np.eye(self.k, dtype=np.uint8), self.matrix])

    def _build_decoder(self, rows: tuple[int, ...]):
        inv = gf.invert_matrix(self.generator()[list(rows)])
        if self.technique == "bitmatrix":
            return BitmatrixEncoder(
                gf.matrix_to_bitmatrix(inv), self.packetsize
            )
        return TableEncoder(inv)


class BitmatrixCodec(_SystematicCodec):
    """Encode/decode driver for codes defined natively by a GF(2)
    bit-matrix (w>8 matrix techniques expanded host-side, and the
    liberation / blaum_roth / liber8tion minimal-density codes, which
    have no GF(2^w) matrix form at all).

    Decode works at the bit level: select the k surviving chunks' w-row
    blocks of the bit generator [I; B], invert the (k*w) x (k*w) GF(2)
    matrix on host (exact), and run the same MXU bulk multiply —
    mirroring the reference's ``jerasure_bitmatrix`` decode structure.
    """

    def __init__(self, bitmatrix: np.ndarray, w: int, packetsize: int):
        super().__init__()
        self.bitmatrix = np.asarray(bitmatrix, np.uint8)
        self.w = w
        self.mw, self.kw = self.bitmatrix.shape
        self.k, self.m = self.kw // w, self.mw // w
        self.packetsize = packetsize
        self.encoder = BitmatrixEncoder(self.bitmatrix, packetsize, w)

    def generator_bits(self) -> np.ndarray:
        """((k+m)*w) x (k*w) bit generator with identity top block."""
        return np.vstack(
            [np.eye(self.kw, dtype=np.uint8), self.bitmatrix]
        )

    def _build_decoder(self, rows: tuple[int, ...]):
        gen = self.generator_bits()
        w = self.w
        sub = np.vstack([gen[r * w:(r + 1) * w] for r in rows])
        return BitmatrixEncoder(
            gf.invert_bitmatrix(sub), self.packetsize, w
        )
