"""Pure-XOR schedule compiler for GF(2) erasure coding.

Every codec in this tree ultimately multiplies a GF(2) bit-matrix by
bit-rows of the data: RS/Cauchy matrices expand through
:func:`gf.matrix_to_bitmatrix` (w=8) / :func:`gfw.matrix_to_bitmatrix`
(w in {16,32}), and the minimal-density RAID-6 codes (liberation,
blaum_roth, liber8tion) are *defined* by their bitmatrix.  The dense
product XORs every selected row per output row — but parity rows share
sub-sums, and "Accelerating XOR-based Erasure Coding using Program
Optimization Techniques" (arXiv:2108.02692) shows greedy common-
subexpression elimination (Paar's algorithm) cuts 30%+ of those XORs.

This module lowers a bitmatrix to an ordered **XOR schedule**: a flat
``[n_steps, 2]`` step table where step ``(dst, src)`` means
``buf[dst] ^= buf[src]`` over u32 words.  Buffers are laid out
``[inputs | outputs | derived]``; non-input buffers start zeroed, so
the first XOR into a buffer is the move and each derived
subexpression is materialized exactly once.  The compiler
(:func:`compile_schedule`) runs Paar's greedy CSE with an incremental
pair-count heap; :class:`XorScheduleEncoder` executes the table
on-device — a single Pallas kernel on TPU
(:func:`ceph_tpu.ec.pallas_kernels.schedule_apply`: scratch accumulator
rows in VMEM, step table in SMEM, one ``fori_loop`` scan) with a jitted
XLA ``fori_loop`` fallback elsewhere — and :class:`ScheduleCache`
memoizes compiled schedules per erasure pattern the way
:class:`~ceph_tpu.recovery.sharded.ShardedDecoder` caches repair LUTs.

Two data layouts cover every codec family:

- ``packet`` — jerasure's packet-interleaved regions (w packets of
  ``packetsize`` bytes per group): the native layout of
  :class:`~ceph_tpu.ec.backend.BitmatrixEncoder` chunks, i.e. every
  bitmatrix-technique codec (cauchy, w>8 RS, minimal-density codes).
- ``bitplane`` — byte-element GF(2^8) chunks (the TableEncoder/RS
  layout): plane ``(j, l)`` holds bit ``l`` of every byte of chunk
  ``j``, so applying ``gf.matrix_to_bitmatrix(R)`` to the planes is
  exactly the byte-wise GF(2^8) product ``R @ chunks``.

The 1701.07731 polynomial-ring transform for blaum_roth (a further
~10% on top of CSE) is noted in README as a follow-on; CSE alone
already clears the 20% bar on the minimal-density decode patterns.
"""

from __future__ import annotations

import heapq
import weakref
from collections import OrderedDict
from dataclasses import dataclass
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ..common.perf_counters import PerfCounters, PerfCountersBuilder, registry
from . import gf


@dataclass(frozen=True)
class XorSchedule:
    """An ordered XOR program computing ``bitmatrix @ rows`` over GF(2).

    ``steps[i] = (dst, src)`` means ``buf[dst] ^= buf[src]``; buffers
    ``[0, n_in)`` are the input rows, ``[n_in, n_in + n_out)`` the
    output rows, and the rest derived subexpressions.  Non-input
    buffers start zeroed (first XOR = move).  ``xor_count`` uses the
    literature's metric (an r-term sum costs r-1 XORs; the move is
    free), so it is directly comparable to ``naive_xor_count`` — the
    dense product's cost on the same matrix.
    """

    steps: np.ndarray  # [n_steps, 2] int32
    n_in: int
    n_out: int
    n_bufs: int
    xor_count: int
    naive_xor_count: int

    @property
    def n_steps(self) -> int:
        return int(self.steps.shape[0])

    @property
    def reduction_fraction(self) -> float:
        """Fraction of the dense product's XORs the CSE removed."""
        if not self.naive_xor_count:
            return 0.0
        return 1.0 - self.xor_count / self.naive_xor_count

    def execute_host(self, words: np.ndarray) -> np.ndarray:
        """Reference interpreter: ``words [n_in, N] u32 -> [n_out, N]``."""
        bufs = np.zeros((self.n_bufs, words.shape[1]), np.uint32)
        bufs[: self.n_in] = words
        for dst, src in self.steps:
            bufs[dst] ^= bufs[src]
        return bufs[self.n_in : self.n_in + self.n_out].copy()


def compile_schedule(
    bitmatrix: np.ndarray, max_derived: int = 1024
) -> XorSchedule:
    """Shrink a GF(2) bit-matrix product into an XOR schedule via
    greedy CSE (Paar's algorithm, arXiv:2108.02692 §3).

    Repeatedly extracts the symbol pair shared by the most rows
    (ties broken deterministically on the pair itself), materializes it
    as a derived symbol for 1 XOR, and substitutes — a pair in c rows
    saves c-1 XORs net, so the schedule's XOR count only ever drops.
    Pair counts are maintained incrementally in a lazy-deletion
    max-heap, so each extraction costs O(affected rows x row width)
    instead of a full matrix rescan.  ``max_derived`` bounds the
    scratch-buffer count (stopping early is always correct).
    """
    bm = (np.asarray(bitmatrix) & 1).astype(bool)
    n_out, n_in = bm.shape
    rows = [set(np.flatnonzero(r).tolist()) for r in bm]
    naive = sum(max(len(r) - 1, 0) for r in rows)
    pair_rows: dict[tuple[int, int], set[int]] = {}
    for ri, r in enumerate(rows):
        syms = sorted(r)
        for i in range(len(syms)):
            for j in range(i + 1, len(syms)):
                pair_rows.setdefault((syms[i], syms[j]), set()).add(ri)
    heap = [(-len(v), p) for p, v in pair_rows.items()]
    heapq.heapify(heap)
    derived: list[tuple[int, int]] = []  # creation-ordered (a, b) defs
    next_sym = n_in

    def _dec(pair: tuple[int, int], ri: int) -> None:
        s = pair_rows.get(pair)
        if s is not None:
            s.discard(ri)
            if not s:
                del pair_rows[pair]

    def _inc(pair: tuple[int, int], ri: int) -> None:
        s = pair_rows.setdefault(pair, set())
        s.add(ri)
        heapq.heappush(heap, (-len(s), pair))

    while len(derived) < max_derived and heap:
        negc, pair = heapq.heappop(heap)
        cur = pair_rows.get(pair)
        if cur is None or len(cur) != -negc:
            continue  # stale heap entry (lazy deletion)
        if -negc < 2:
            break  # no pair shared by >= 2 rows: CSE is done
        a, b = pair
        s = next_sym
        next_sym += 1
        derived.append((a, b))
        del pair_rows[pair]
        for ri in sorted(cur):
            r = rows[ri]
            r.discard(a)
            r.discard(b)
            for x in r:
                _dec((a, x) if a < x else (x, a), ri)
                _dec((b, x) if b < x else (x, b), ri)
            for x in r:
                _inc((s, x) if s < x else (x, s), ri)
            r.add(s)

    # emit: derived defs in creation order (each references only inputs
    # and earlier derived symbols), then the surviving output sums
    def buf(sym: int) -> int:
        return sym if sym < n_in else sym + n_out

    steps: list[tuple[int, int]] = []
    for i, (a, b) in enumerate(derived):
        d = n_in + n_out + i
        steps.append((d, buf(a)))
        steps.append((d, buf(b)))
    for ri, r in enumerate(rows):
        dst = n_in + ri
        for sym in sorted(r):
            steps.append((dst, buf(sym)))
    xor = len(derived) + sum(max(len(r) - 1, 0) for r in rows)
    return XorSchedule(
        steps=np.asarray(steps, np.int32).reshape(-1, 2),
        n_in=n_in,
        n_out=n_out,
        n_bufs=n_in + n_out + len(derived),
        xor_count=xor,
        naive_xor_count=naive,
    )


# ---------------------------------------------------------------------------
# data layouts: chunk bytes <-> u32 word rows the schedule operates on


def packet_words(size: int, w: int, packetsize: int) -> int:
    """Words per row for the packet layout of a ``size``-byte chunk."""
    pb = (packetsize + 3) // 4 * 4
    return size // (w * packetsize) * (pb // 4)


def pack_packet_rows(
    data: np.ndarray, w: int, packetsize: int
) -> np.ndarray:
    """Packet-interleave ``[k, S] u8 -> [k*w, NW] u32`` (row ``j*w+l``
    = chunk j's packets l across groups, each packet tail-padded to a
    whole word — XOR of zero-padded packets is the zero-padded XOR, so
    the pad trims off exactly on unpack)."""
    k, size = data.shape
    p = packetsize
    group = w * p
    if size % group:
        raise ValueError(f"chunk size {size} % w*packetsize={group} != 0")
    g = size // group
    pb = (p + 3) // 4 * 4
    d = np.ascontiguousarray(data).reshape(k, g, w, p)
    d = d.transpose(0, 2, 1, 3).reshape(k * w, g, p)
    if pb != p:
        d = np.pad(d, ((0, 0), (0, 0), (0, pb - p)))
    return np.ascontiguousarray(d).view(np.uint32).reshape(k * w, g * (pb // 4))


def unpack_packet_rows(
    words: np.ndarray, n_chunks: int, w: int, packetsize: int, size: int
) -> np.ndarray:
    """Inverse of :func:`pack_packet_rows`: ``[n*w, NW] u32 -> [n, S]``."""
    p = packetsize
    g = size // (w * p)
    pb = (p + 3) // 4 * 4
    b = np.ascontiguousarray(words).view(np.uint8)
    b = b.reshape(n_chunks * w, g, pb)[:, :, :p]
    b = b.reshape(n_chunks, w, g, p).transpose(0, 2, 1, 3)
    return np.ascontiguousarray(b.reshape(n_chunks, size))


def bitplane_words(size: int) -> int:
    """Words per plane for the bit-plane layout of a ``size``-byte chunk."""
    return ((size + 31) // 32 * 32) // 32


def pack_bitplanes(data: np.ndarray) -> np.ndarray:
    """Byte-element layout ``[k, S] u8 -> [k*8, NW] u32``: plane
    ``j*8+l`` packs bit ``l`` of every byte of chunk j (little-endian
    within the plane bytes), so ``gf.matrix_to_bitmatrix(R)`` applied
    to the planes is exactly the byte-wise GF(2^8) product."""
    k, size = data.shape
    pad = (-size) % 32
    if pad:
        data = np.pad(data, ((0, 0), (0, pad)))
    shifts = np.arange(8, dtype=np.uint8)[None, :, None]
    bits = (data[:, None, :] >> shifts) & 1
    planes = np.packbits(
        bits.reshape(k * 8, -1), axis=-1, bitorder="little"
    )
    return np.ascontiguousarray(planes).view(np.uint32)


def unpack_bitplanes(
    words: np.ndarray, n_chunks: int, size: int
) -> np.ndarray:
    """Inverse of :func:`pack_bitplanes`: ``[n*8, NW] u32 -> [n, S]``."""
    planes = np.ascontiguousarray(words).view(np.uint8)
    bits = np.unpackbits(planes, axis=-1, bitorder="little")
    bits = bits.reshape(n_chunks, 8, -1)[:, :, :size]
    shifts = np.arange(8, dtype=np.uint8)[None, :, None]
    return np.ascontiguousarray(
        (bits << shifts).sum(axis=1, dtype=np.uint8)
    )


# ---------------------------------------------------------------------------
# device execution


@partial(jax.jit, static_argnames=("n_out", "n_bufs"))
def _xla_apply(steps, d_words, n_out, n_bufs):
    """XLA fallback interpreter: the same buffer semantics as the
    Pallas kernel, as a ``fori_loop`` over dynamic row slices.  Jitted
    per (n_steps, word-width, n_bufs) shape, so repeated decodes of one
    pattern reuse the executable (the schedule-cache compile-once
    contract on CPU)."""
    n_in = d_words.shape[0]
    bufs = jnp.zeros((n_bufs, d_words.shape[1]), jnp.uint32)
    bufs = bufs.at[:n_in].set(d_words)

    def body(i, b):
        dst = steps[i, 0]
        src = steps[i, 1]
        row = jax.lax.dynamic_index_in_dim(b, dst, 0, keepdims=True)
        srow = jax.lax.dynamic_index_in_dim(b, src, 0, keepdims=True)
        return jax.lax.dynamic_update_slice_in_dim(b, row ^ srow, dst, 0)

    bufs = jax.lax.fori_loop(
        jnp.int32(0), jnp.int32(steps.shape[0]), body, bufs
    )
    return bufs[n_in : n_in + n_out]


class XorScheduleEncoder:
    """Execute a compiled XOR schedule for one repair bitmatrix.

    Mirrors the executor's ``encode_async`` / host-materialize split:
    ``encode_async`` packs chunk bytes into word rows (host), dispatches
    the device scan, and returns the in-flight ``[n_out_bits, NW]`` u32
    array; ``finalize`` materializes, trims padding, and re-packs to
    ``[n_chunks, S]`` bytes.  ``layout`` picks the byte<->row mapping:
    ``"packet"`` (bitmatrix codecs, w + packetsize) or ``"bitplane"``
    (byte-element GF(2^8) chunks, w fixed at 8).
    """

    def __init__(
        self,
        bitmatrix: np.ndarray,
        layout: str = "packet",
        w: int = 8,
        packetsize: int = 64,
        max_derived: int = 1024,
        use_pallas: bool | None = None,
        interpret: bool | None = None,
    ):
        if layout not in ("packet", "bitplane"):
            raise ValueError(f"unknown schedule layout {layout!r}")
        self.bitmatrix = np.asarray(bitmatrix, np.uint8) & 1
        self.layout = layout
        self.w = w if layout == "packet" else 8
        self.packetsize = packetsize
        self.schedule = compile_schedule(self.bitmatrix, max_derived)
        self.n_chunks_out = self.schedule.n_out // self.w
        on_tpu = jax.default_backend() == "tpu"
        self._use_pallas = on_tpu if use_pallas is None else use_pallas
        self._interpret = (not on_tpu) if interpret is None else interpret
        self._steps = jnp.asarray(self.schedule.steps)

    def _pack(self, data: np.ndarray) -> np.ndarray:
        if self.layout == "packet":
            return pack_packet_rows(data, self.w, self.packetsize)
        return pack_bitplanes(data)

    def encode_async(self, data: np.ndarray, device=None):
        """``data [k, S] u8`` -> in-flight ``[n_out_bits, NW] u32``."""
        words = self._pack(np.asarray(data, np.uint8))
        sched = self.schedule
        if self._use_pallas:
            from .pallas_kernels import schedule_apply

            return schedule_apply(
                self._steps,
                words,
                sched.n_out,
                sched.n_bufs,
                interpret=self._interpret,
                device=device,
            )
        if device is not None:
            words = jax.device_put(words, device)
        return _xla_apply(
            self._steps, jnp.asarray(words), sched.n_out, sched.n_bufs
        )

    def finalize(self, out, size: int) -> np.ndarray:
        """Materialize an in-flight output for ``size``-byte chunks."""
        arr = np.asarray(out)
        if self.layout == "packet":
            nw = packet_words(size, self.w, self.packetsize)
            return unpack_packet_rows(
                arr[:, :nw], self.n_chunks_out, self.w, self.packetsize, size
            )
        nw = bitplane_words(size)
        return unpack_bitplanes(arr[:, :nw], self.n_chunks_out, size)

    def encode(self, data: np.ndarray) -> np.ndarray:
        """``data [k, S] u8 -> [n_chunks_out, S] u8`` (synchronous)."""
        return self.finalize(self.encode_async(data), data.shape[1])


class DenseBitmatrixAdapter:
    """``encode_async``/``finalize`` shim over the dense
    :class:`~ceph_tpu.ec.backend.BitmatrixEncoder` MXU product, so the
    executor's bit-level dispatch is engine-agnostic (the
    ``recovery_xor_schedule = off`` reference path)."""

    schedule = None  # no XOR schedule: the cache skips its counters

    def __init__(self, bitmatrix: np.ndarray, w: int, packetsize: int):
        from .backend import BitmatrixEncoder

        self._enc = BitmatrixEncoder(
            np.asarray(bitmatrix, np.uint8), packetsize, w
        )

    def encode_async(self, data: np.ndarray, device=None):
        group = self._enc.w * self._enc.packetsize
        if data.shape[1] % group:
            raise ValueError(
                f"chunk size {data.shape[1]} not a multiple of "
                f"w*packetsize={group}"
            )
        arr = (
            jnp.asarray(data)
            if device is None
            else jax.device_put(np.asarray(data), device)
        )
        return self._enc._encode(arr)

    def finalize(self, out, size: int) -> np.ndarray:
        return np.asarray(out)


# ---------------------------------------------------------------------------
# caching + observability


def _build_counters() -> PerfCounters:
    return (
        PerfCountersBuilder("ec_schedule")
        .add_u64_counter(
            "schedules_compiled", "XOR schedules compiled (CSE passes run)"
        )
        .add_u64_counter(
            "schedule_xor_count",
            "total XORs across compiled schedules (post-CSE)",
        )
        .add_u64_counter(
            "schedule_xor_naive",
            "total XORs the dense bit-matrix products would have cost",
        )
        .add_u64_counter(
            "schedule_cache_hits",
            "schedule-cache lookups served without recompiling",
        )
        .add_u64_counter(
            "schedule_cache_evictions",
            "cached engines evicted by the LRU bound "
            "(recovery_schedule_cache_max)",
        )
        .add_u64_counter(
            "schedules_quarantined",
            "compiled engines evicted + blacklisted after their output "
            "failed decode-verify (miscompiled XOR schedules)",
        )
        .create_perf_counters()
    )


def schedule_counters() -> PerfCounters:
    """The process-wide ``ec_schedule`` perf-counter component."""
    return registry().get("ec_schedule") or _build_counters()


# every live cache, for the admin socket's dump_ec_schedules hook
_LIVE_CACHES: weakref.WeakSet = weakref.WeakSet()


class ScheduleCache:
    """Compiled-schedule cache, one entry per (engine, erasure pattern)
    — the :class:`~ceph_tpu.recovery.sharded.ShardedDecoder` LUT-cache
    pattern applied to XOR schedules.  Hits and compile-time XOR
    counters land in the ``ec_schedule`` perf component (Prometheus
    scrapes it through the shared registry); live caches self-register
    for the ``dump_ec_schedules`` admin hook.

    ``max_entries`` bounds the cache LRU (``recovery_schedule_cache_max``
    at the executor surface; 0 = unbounded): a long chaos timeline
    visits many erasure patterns and must not grow device executables
    without limit.  :meth:`quarantine` is the decode-verify eviction
    path — an engine whose output failed CRC verification is dropped
    AND blacklisted, so :func:`encoder_for_group` reroutes that pattern
    to the dense reference engine instead of recompiling the same bad
    schedule.
    """

    def __init__(self, name: str = "recovery", max_entries: int = 0):
        self.name = name
        self.max_entries = int(max_entries)
        self._entries: OrderedDict = OrderedDict()
        self._quarantined: set = set()
        self.pc = schedule_counters()
        _LIVE_CACHES.add(self)

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, key, build):
        """Fetch the engine for ``key``, building (and counting) once;
        refreshes the key's LRU position and evicts past the bound."""
        enc = self._entries.get(key)
        if enc is not None:
            self._entries.move_to_end(key)
            self.pc.inc("schedule_cache_hits")
            return enc
        enc = self._entries[key] = build()
        sched = getattr(enc, "schedule", None)
        if sched is not None:
            self.pc.inc("schedules_compiled")
            self.pc.inc("schedule_xor_count", sched.xor_count)
            self.pc.inc("schedule_xor_naive", sched.naive_xor_count)
        if self.max_entries > 0:
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.pc.inc("schedule_cache_evictions")
        return enc

    def quarantine(self, key) -> bool:
        """Evict AND blacklist ``key`` (decode-verify caught its engine
        shipping wrong bytes).  Returns True the first time — callers
        journal ``scrub.schedule_quarantined`` exactly once per key."""
        self._entries.pop(key, None)
        if key in self._quarantined:
            return False
        self._quarantined.add(key)
        self.pc.inc("schedules_quarantined")
        return True

    def is_quarantined(self, key) -> bool:
        return key in self._quarantined

    def dump(self) -> dict:
        entries = []
        for key, enc in sorted(
            self._entries.items(), key=lambda kv: str(kv[0])
        ):
            e: dict = {"key": str(key)}
            sched = getattr(enc, "schedule", None)
            if sched is None:
                e["engine"] = "dense"
            else:
                e.update(
                    engine="schedule",
                    n_steps=sched.n_steps,
                    n_in=sched.n_in,
                    n_out=sched.n_out,
                    xor_count=sched.xor_count,
                    naive_xor_count=sched.naive_xor_count,
                    reduction_fraction=round(sched.reduction_fraction, 4),
                )
            entries.append(e)
        return {
            "name": self.name,
            "entries": entries,
            "max_entries": self.max_entries,
            "quarantined": sorted(str(k) for k in self._quarantined),
        }


def dump_ec_schedules() -> dict:
    """Admin-socket hook body: every live schedule cache plus the
    aggregate ``ec_schedule`` counters."""
    return {
        "caches": sorted(
            (c.dump() for c in _LIVE_CACHES), key=lambda d: d["name"]
        ),
        "counters": schedule_counters().dump(),
    }


def encoder_for_group(cache: ScheduleCache, group, mode: str):
    """Build-or-fetch the batched-decode engine for one pattern group.

    Bit-level groups (``repair_bitmatrix`` set — bitmatrix-native and
    cauchy-technique codecs) run the XOR schedule in packet layout, or
    the dense MXU product when ``mode == "off"``.  GF(2^8) table groups
    reach here only when ``mode == "on"`` forces them onto the schedule
    path: their repair matrix expands through
    :func:`gf.matrix_to_bitmatrix` and executes in bit-plane layout,
    byte-identical to the LUT product.

    A pattern whose schedule was quarantined (decode-verify caught it
    shipping wrong bytes) permanently reroutes to the dense reference
    engine — same repair bitmatrix, independent execution path.
    """
    if group.repair_bitmatrix is not None:
        if mode == "off" or cache.is_quarantined(("packet", group.mask)):
            return cache.get(
                ("dense", group.mask),
                lambda: DenseBitmatrixAdapter(
                    group.repair_bitmatrix, group.w, group.packetsize
                ),
            )
        return cache.get(
            ("packet", group.mask),
            lambda: XorScheduleEncoder(
                group.repair_bitmatrix,
                layout="packet",
                w=group.w,
                packetsize=group.packetsize,
            ),
        )
    return cache.get(
        ("bitplane", group.mask),
        lambda: XorScheduleEncoder(
            gf.matrix_to_bitmatrix(group.repair_matrix), layout="bitplane"
        ),
    )
