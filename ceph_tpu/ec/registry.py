"""EC plugin registry: profile strings -> codec instances.

Parity with the reference's ``src/erasure-code/ErasureCodePlugin.{h,cc}``
(``ErasureCodePluginRegistry::{instance,load,add,get,factory}``), minus
``dlopen``: plugins register via :func:`register_plugin` (the
``__erasure_code_init`` analog) at import, or lazily through the
built-in table.  Profiles are string->string maps exactly like the
reference's (``plugin=``, ``k``, ``m``, ``technique``, ``w``,
``packetsize``, ``crush-failure-domain``, ...).
"""

from __future__ import annotations

from typing import Callable

from .interface import ErasureCodeInterface, ErasureCodeError, Profile

_PLUGINS: dict[str, Callable[[], "type[ErasureCodeInterface]"]] = {}


def register_plugin(name: str, loader: Callable[[], type]) -> None:
    _PLUGINS[name] = loader


def _builtin(name: str):
    if name in ("jerasure", "jax"):
        from .plugins.jerasure import ErasureCodeJerasure

        return ErasureCodeJerasure
    if name == "isa":
        from .plugins.isa import ErasureCodeIsa

        return ErasureCodeIsa
    if name == "lrc":
        from .plugins.lrc import ErasureCodeLrc

        return ErasureCodeLrc
    if name == "clay":
        from .plugins.clay import ErasureCodeClay

        return ErasureCodeClay
    if name == "shec":
        from .plugins.shec import ErasureCodeShec

        return ErasureCodeShec
    return None


class ErasureCodePluginRegistry:
    """Singleton factory keyed by plugin name."""

    _instance: "ErasureCodePluginRegistry | None" = None

    @classmethod
    def instance(cls) -> "ErasureCodePluginRegistry":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def load(self, name: str):
        if name in _PLUGINS:
            return _PLUGINS[name]()
        klass = _builtin(name)
        if klass is None:
            raise ErasureCodeError(f"unknown erasure-code plugin {name!r}")
        return klass

    def factory(self, profile: dict[str, str] | Profile) -> ErasureCodeInterface:
        if isinstance(profile, dict):
            profile = Profile(dict(profile))
        name = profile.get("plugin", "jerasure")
        klass = self.load(name)
        ec = klass()
        ec.init(profile)
        return ec


def create(profile: dict[str, str]) -> ErasureCodeInterface:
    """Convenience: build + init a codec from a profile dict."""
    return ErasureCodePluginRegistry.instance().factory(profile)
