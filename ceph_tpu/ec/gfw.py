"""General-w GF(2^w) arithmetic and native bit-matrix code constructions.

Extends :mod:`ceph_tpu.ec.gf` (which is specialized to the w=8 table
path) with what the reference's jerasure plugin family needs beyond
w=8 (upstream ``src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc}``
class list, SURVEY.md §2.2.3):

- w in {8, 16, 32} for the matrix techniques (``reed_sol_van``,
  ``reed_sol_r6_op``, ``cauchy_orig``, ``cauchy_good``).  Instead of
  porting gf-complete's per-w SIMD multiply kernels, every w>8 matrix
  is expanded once (host) to its (m*w) x (k*w) GF(2) bit-matrix
  (``jerasure_matrix_to_bitmatrix`` semantics) and executed on the
  MXU — the TPU has no byte-table gather path worth using, but GF(2)
  matmul is native.
- The minimal-density RAID-6 bit-matrix codes: ``liberation`` (w
  prime, Plank's Liberation construction), ``blaum_roth`` (w+1 prime,
  ring GF(2)[x]/(1+x+...+x^w)), and ``liber8tion`` (w=8; matrices
  found by an in-repo deterministic search, embedded as constants the
  same way the reference embeds its searched matrices).  All three
  are validated at construction time against the RAID-6 MDS
  characterization (every X_i and every X_i ^ X_j invertible); the
  exact bit layouts are pinned by the non-regression archive.

Polynomials are gf-complete's defaults: 0x11d (w=8), 0x1100b (w=16),
0x400007 (w=32).
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

# gf-complete's default polynomials.  Convention wrinkle: w<=16 entries
# include the x^w term (0x11D = x^8+x^4+x^3+x^2+1); the w=32 one omits
# it (0x400007 = the low bits of x^32+x^22+x^2+x+1) because it would
# not fit the library's u32 — normalize to always include x^w.
PRIM_POLY = {4: 0x13, 8: 0x11D, 16: 0x1100B, 32: 0x400007 | (1 << 32)}


def gf_mult(a: int, b: int, w: int) -> int:
    """Carry-less multiply with per-step reduction (Russian peasant)."""
    poly = PRIM_POLY[w] | (1 << w)
    top = 1 << w
    r = 0
    while b:
        if b & 1:
            r ^= a
        b >>= 1
        a <<= 1
        if a & top:
            a ^= poly
    return r


def gf_inv(a: int, w: int) -> int:
    """a^(2^w - 2) by square-and-multiply."""
    if a == 0:
        raise ZeroDivisionError("gf_inv(0)")
    e = (1 << w) - 2
    r = 1
    base = a
    while e:
        if e & 1:
            r = gf_mult(r, base, w)
        base = gf_mult(base, base, w)
        e >>= 1
    return r


def gf_div(a: int, b: int, w: int) -> int:
    if b == 0:
        raise ZeroDivisionError("gf_div by 0")
    if a == 0:
        return 0
    return gf_mult(a, gf_inv(b, w), w)


def vandermonde_matrix(k: int, m: int, w: int) -> np.ndarray:
    """reed_sol_van semantics at width w (extended Vandermonde,
    systematized by column operations; bottom m rows returned).
    Matches :func:`ceph_tpu.ec.gf.vandermonde_matrix` for w=8."""
    rows = k + m
    if rows > (1 << w):
        raise ValueError(f"k + m must be <= 2^{w}")
    v = np.zeros((rows, k), np.uint64)
    v[0, 0] = 1
    for i in range(1, rows - 1):
        e = 1
        for j in range(k):
            v[i, j] = e
            e = gf_mult(e, i, w)
    v[rows - 1, k - 1] = 1
    for i in range(1, k):
        pr = next((r for r in range(i, rows) if v[r, i] != 0), None)
        if pr is None:
            raise ValueError("singular vandermonde block")
        if pr != i:
            v[[pr, i]] = v[[i, pr]]
        if v[i, i] != 1:
            inv = gf_inv(int(v[i, i]), w)
            for r in range(rows):
                v[r, i] = gf_mult(int(v[r, i]), inv, w)
        for j in range(k):
            f = int(v[i, j])
            if j != i and f != 0:
                for r in range(rows):
                    v[r, j] ^= gf_mult(f, int(v[r, i]), w)
    return v[k:].copy()


def raid6_matrix(k: int, w: int) -> np.ndarray:
    out = np.zeros((2, k), np.uint64)
    e = 1
    for j in range(k):
        out[0, j] = 1
        out[1, j] = e
        e = gf_mult(e, 2, w)
    return out


def cauchy_matrix(k: int, m: int, w: int) -> np.ndarray:
    if k + m > (1 << w):
        raise ValueError(f"k + m must be <= 2^{w}")
    out = np.zeros((m, k), np.uint64)
    for i in range(m):
        for j in range(k):
            d = i ^ (m + j)
            if d == 0:
                raise ValueError("cauchy index collision")
            out[i, j] = gf_inv(d, w)
    return out


def cauchy_good_matrix(k: int, m: int, w: int) -> np.ndarray:
    mat = cauchy_matrix(k, m, w)
    for j in range(k):
        f = int(mat[0, j])
        if f != 1:
            inv = gf_inv(f, w)
            for i in range(m):
                mat[i, j] = gf_mult(int(mat[i, j]), inv, w)
    for i in range(1, m):
        f = int(mat[i, 0])
        if f != 1:
            inv = gf_inv(f, w)
            for j in range(k):
                mat[i, j] = gf_mult(int(mat[i, j]), inv, w)
    return mat


def matrix_to_bitmatrix(matrix: np.ndarray, w: int) -> np.ndarray:
    """Expand m x k GF(2^w) to (m*w) x (k*w) GF(2): block (i,j) column
    l holds the bits of M[i][j] * alpha^l (the
    ``jerasure_matrix_to_bitmatrix`` layout, generalized from
    :func:`ceph_tpu.ec.gf.matrix_to_bitmatrix`)."""
    m, k = matrix.shape
    out = np.zeros((m * w, k * w), np.uint8)
    for i in range(m):
        for j in range(k):
            e = int(matrix[i, j])
            for l in range(w):
                for t in range(w):
                    out[i * w + t, j * w + l] = (e >> t) & 1
                e = gf_mult(e, 2, w)
    return out


# ---- GF(2) helpers ----


def _invertible_gf2(mat: np.ndarray) -> bool:
    n = mat.shape[0]
    a = (mat & 1).astype(np.uint8).copy()
    for col in range(n):
        pr = next((r for r in range(col, n) if a[r, col]), None)
        if pr is None:
            return False
        if pr != col:
            a[[pr, col]] = a[[col, pr]]
        for r in range(n):
            if r != col and a[r, col]:
                a[r] ^= a[col]
    return True


def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    for p in range(2, int(n**0.5) + 1):
        if n % p == 0:
            return False
    return True


def _assert_raid6_mds(blocks: list[np.ndarray], name: str) -> None:
    """RAID-6 (m=2) MDS characterization: every Q block X_i and every
    pairwise sum X_i ^ X_j must be invertible over GF(2)."""
    for i, b in enumerate(blocks):
        if not _invertible_gf2(b):
            raise ValueError(f"{name}: X_{i} singular")
        for j in range(i):
            if not _invertible_gf2(blocks[j] ^ b):
                raise ValueError(f"{name}: X_{j} ^ X_{i} singular")


def _raid6_bitmatrix(blocks: list[np.ndarray], w: int) -> np.ndarray:
    """Assemble [P; Q] rows: P = identity blocks, Q = the X_i."""
    k = len(blocks)
    bm = np.zeros((2 * w, k * w), np.uint8)
    eye = np.eye(w, dtype=np.uint8)
    for i, X in enumerate(blocks):
        bm[:w, i * w:(i + 1) * w] = eye
        bm[w:, i * w:(i + 1) * w] = X
    return bm


# ---- minimal-density RAID-6 constructions ----


def liberation_bitmatrix(k: int, w: int) -> np.ndarray:
    """Plank's RAID-6 Liberation code: w prime > 2, k <= w.

    Q block i is the cyclic shift sigma^i plus, for i >= 1, one extra
    bit at row (i*(w-1)/2) mod w — exactly kw + k - 1 ones in Q, the
    minimal density bound.  MDS-validated at construction.
    """
    if not _is_prime(w) or w <= 2:
        raise ValueError(f"liberation requires prime w > 2, got {w}")
    if not (1 <= k <= w):
        raise ValueError(f"liberation requires k <= w ({k} > {w})")
    blocks = []
    for i in range(k):
        X = np.zeros((w, w), np.uint8)
        for j in range(w):
            X[j, (j + i) % w] = 1
        if i >= 1:
            j = (i * ((w - 1) // 2)) % w
            X[j, (j + i - 1) % w] ^= 1
        blocks.append(X)
    _assert_raid6_mds(blocks, f"liberation(k={k}, w={w})")
    return _raid6_bitmatrix(blocks, w)


def blaum_roth_bitmatrix(k: int, w: int) -> np.ndarray:
    """Blaum-Roth RAID-6 code: w+1 prime, k <= w.

    Q block i is multiplication by x^i in the ring
    GF(2)[x] / (1 + x + ... + x^w); MDS because w+1 is prime
    (validated explicitly anyway).
    """
    if not _is_prime(w + 1):
        raise ValueError(f"blaum_roth requires w+1 prime, got w={w}")
    if not (1 <= k <= w):
        raise ValueError(f"blaum_roth requires k <= w ({k} > {w})")
    X = np.zeros((w, w), np.uint8)
    for j in range(w - 1):
        X[j + 1, j] = 1
    X[:, w - 1] = 1  # x * x^{w-1} = x^w = 1 + x + ... + x^{w-1}
    blocks = []
    Xi = np.eye(w, dtype=np.uint8)
    for _ in range(k):
        blocks.append(Xi)
        Xi = (X @ Xi) % 2
    _assert_raid6_mds(blocks, f"blaum_roth(k={k}, w={w})")
    return _raid6_bitmatrix(blocks, w)


# Q blocks for the liber8tion-parameter codes (w=8, m=2, k<=8), one
# row-int tuple per block (bit c of entry j = X[j][c]).  Found by an
# in-repo deterministic backtracking search over near-minimal-density
# block families (cyclic shift + <=2 extra bits, distinct shifts,
# RAID-6 pairwise-invertibility pruning) — the same "searched, then
# embedded" approach the reference uses for this technique, with the
# search (and the MDS re-check below) reproducible from this file.
_LIBER8TION_BLOCKS: dict[int, tuple] = {
    2: ((1, 2, 4, 8, 16, 32, 64, 128), (3, 4, 8, 16, 32, 64, 128, 1)),
    3: ((1, 2, 4, 8, 16, 32, 64, 128), (3, 4, 8, 16, 32, 64, 128, 1),
        (5, 10, 16, 32, 64, 128, 1, 2)),
    4: ((1, 2, 4, 8, 16, 32, 64, 128), (3, 4, 8, 16, 32, 64, 128, 1),
        (5, 10, 16, 32, 64, 128, 1, 2), (8, 18, 32, 64, 128, 1, 2, 4)),
    5: ((1, 2, 4, 8, 16, 32, 64, 128), (3, 4, 8, 16, 32, 64, 128, 1),
        (5, 10, 16, 32, 64, 128, 1, 2), (8, 18, 32, 64, 128, 1, 2, 4),
        (64, 128, 5, 130, 4, 8, 16, 32)),
    6: ((1, 2, 4, 8, 16, 32, 64, 128), (3, 4, 8, 16, 32, 64, 128, 1),
        (5, 10, 16, 32, 64, 128, 1, 2), (8, 20, 40, 64, 128, 1, 2, 4),
        (64, 128, 1, 6, 4, 8, 144, 32), (128, 1, 2, 4, 40, 16, 32, 65)),
}


def _companion_power_blocks(k: int, w: int = 8) -> list[np.ndarray]:
    """Q blocks X_i = C^i where C is the companion matrix of the w=8
    primitive polynomial: X_a ^ X_b = C^a (I ^ C^(b-a)) is invertible
    for any a != b because C has multiplicative order 2^w - 1, so this
    family is RAID-6 MDS for any k < 2^w - 1."""
    poly = PRIM_POLY[w] & ((1 << w) - 1)
    C = np.zeros((w, w), np.uint8)
    for j in range(w - 1):
        C[j + 1, j] = 1
    for t in range(w):
        C[t, w - 1] = (poly >> t) & 1
    blocks = []
    Xi = np.eye(w, dtype=np.uint8)
    for _ in range(k):
        blocks.append(Xi)
        Xi = (C @ Xi) % 2
    return blocks


def liber8tion_bitmatrix(k: int) -> np.ndarray:
    """liber8tion-parameter RAID-6 code: w = 8, m = 2, k <= 8.

    w=8 is neither prime (liberation) nor w+1-prime (blaum_roth), so
    upstream's codes come from search.  k <= 6 uses the in-repo
    searched near-minimal-density blocks (``_LIBER8TION_BLOCKS``);
    k in {7, 8} uses companion-matrix powers — denser in Q, but Q
    density is a CPU XOR-count metric with no effect on the MXU
    matmul path, and erasure tolerance is identical.
    """
    w = 8
    if not (1 <= k <= w):
        raise ValueError(f"liber8tion requires k <= 8, got {k}")
    if k == 1:
        blocks = [np.eye(w, dtype=np.uint8)]
    elif k in _LIBER8TION_BLOCKS:
        blocks = []
        for rows in _LIBER8TION_BLOCKS[k]:
            X = np.zeros((w, w), np.uint8)
            for j, rowbits in enumerate(rows):
                for c in range(w):
                    X[j, c] = (rowbits >> c) & 1
            blocks.append(X)
    else:
        blocks = _companion_power_blocks(k, w)
    _assert_raid6_mds(blocks, f"liber8tion(k={k})")
    return _raid6_bitmatrix(blocks, w)


@lru_cache(maxsize=None)
def bitmatrix_for(technique: str, k: int, m: int, w: int) -> bytes:
    """Cached native-bitmatrix construction dispatch (bytes for
    hashability; reshape to (m*w, k*w))."""
    if technique == "liberation":
        bm = liberation_bitmatrix(k, w)
    elif technique == "blaum_roth":
        bm = blaum_roth_bitmatrix(k, w)
    elif technique == "liber8tion":
        if w != 8:
            raise ValueError("liber8tion is a w=8 code")
        bm = liber8tion_bitmatrix(k)
    else:
        raise ValueError(f"unknown native bitmatrix technique {technique!r}")
    if m != 2:
        raise ValueError(f"{technique} is a RAID-6 (m=2) code, got m={m}")
    return bm.tobytes()
