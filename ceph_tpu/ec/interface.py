"""Erasure-code contract + shared chunking logic.

API parity with the reference's ``src/erasure-code/ErasureCodeInterface.h``
(``init``, ``get_chunk_count``, ``get_data_chunk_count``,
``get_chunk_size``, ``get_sub_chunk_count``, ``minimum_to_decode``,
``minimum_to_decode_with_cost``, ``encode``, ``encode_chunks``,
``decode``, ``decode_chunks``, ``get_chunk_mapping``, ``decode_concat``)
and the shared pad/align/split logic of
``src/erasure-code/ErasureCode.{h,cc}`` (``ErasureCode::encode`` ->
``encode_prepare`` -> ``encode_chunks``).  Plugins subclass
:class:`ErasureCode` and override ``encode_chunks``/``decode_chunks``
(+ ``minimum_to_decode`` for locality-aware codes).

Chunks are numpy uint8 arrays here (the bufferlist equivalent); device
plugins move them to the TPU inside ``encode_chunks``.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


class ErasureCodeError(Exception):
    pass


@dataclass
class Profile:
    """String->string EC profile (reference plugin profiles)."""

    values: dict[str, str] = field(default_factory=dict)

    def get(self, key: str, default: str | None = None) -> str | None:
        return self.values.get(key, default)

    def get_int(self, key: str, default: int) -> int:
        v = self.values.get(key)
        return int(v) if v not in (None, "") else default

    def __getitem__(self, key: str) -> str:
        return self.values[key]

    def __contains__(self, key: str) -> bool:
        return key in self.values


class ErasureCodeInterface:
    """Abstract EC contract (reference ErasureCodeInterface.h)."""

    def init(self, profile: Profile) -> None:
        raise NotImplementedError

    def get_chunk_count(self) -> int:
        raise NotImplementedError

    def get_data_chunk_count(self) -> int:
        raise NotImplementedError

    def get_coding_chunk_count(self) -> int:
        return self.get_chunk_count() - self.get_data_chunk_count()

    def get_sub_chunk_count(self) -> int:
        return 1

    def get_chunk_size(self, stripe_width: int) -> int:
        raise NotImplementedError

    def get_chunk_mapping(self) -> list[int]:
        return []

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> set[int]:
        raise NotImplementedError

    def minimum_to_decode_with_cost(
        self, want_to_read: set[int], available: dict[int, int]
    ) -> set[int]:
        raise NotImplementedError

    def encode(
        self, want_to_encode: set[int], data: bytes | np.ndarray
    ) -> dict[int, np.ndarray]:
        raise NotImplementedError

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        raise NotImplementedError

    def decode(
        self,
        want_to_read: set[int],
        chunks: dict[int, np.ndarray],
        chunk_size: int,
    ) -> dict[int, np.ndarray]:
        raise NotImplementedError

    def decode_chunks(
        self, want_to_read: set[int], chunks: dict[int, np.ndarray]
    ) -> dict[int, np.ndarray]:
        raise NotImplementedError

    def decode_concat(self, chunks: dict[int, np.ndarray]) -> bytes:
        raise NotImplementedError


class ErasureCode(ErasureCodeInterface):
    """Shared chunking/padding base (reference ErasureCode.cc)."""

    k: int = 0
    m: int = 0
    chunk_mapping: list[int] = []

    # ---- helpers plugins override ----

    def get_alignment(self) -> int:
        """Stripe alignment in bytes; chunk_size rounds the padded
        object up to a multiple of this before dividing by k."""
        return self.k * 8

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_chunk_size(self, stripe_width: int) -> int:
        alignment = self.get_alignment()
        tail = stripe_width % alignment
        padded = stripe_width + (alignment - tail if tail else 0)
        assert padded % self.k == 0
        return padded // self.k

    def get_chunk_mapping(self) -> list[int]:
        return list(self.chunk_mapping)

    def _chunk_index(self, i: int) -> int:
        """Shard id for raw chunk position i (reference to_mapping)."""
        return self.chunk_mapping[i] if self.chunk_mapping else i

    # ---- minimum_to_decode (reference default: any k available) ----

    def _minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> set[int]:
        if want_to_read <= available:
            return set(want_to_read)
        if len(available) < self.k:
            raise ErasureCodeError(
                f"need {self.k} chunks, only {len(available)} available"
            )
        minimum = set(want_to_read & available)
        for c in sorted(available):
            if len(minimum) == self.k:
                break
            minimum.add(c)
        return minimum

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> set[int]:
        return self._minimum_to_decode(want_to_read, available)

    def minimum_to_decode_with_cost(
        self, want_to_read: set[int], available: dict[int, int]
    ) -> set[int]:
        # default: cost-blind (reference base class does the same)
        return self.minimum_to_decode(want_to_read, set(available))

    # ---- create_rule (reference ErasureCode::create_rule) ----
    #
    # The bridge that makes an EC profile self-contained: the profile's
    # ``crush-root`` / ``crush-failure-domain`` / ``crush-device-class``
    # keys describe the CRUSH rule the pool needs, and the plugin builds
    # it on the map (upstream src/erasure-code/ErasureCode.cc ::
    # create_rule, defaults from ErasureCode::parse).

    DEFAULT_RULE_ROOT = "default"
    DEFAULT_RULE_FAILURE_DOMAIN = "host"

    def _rule_profile(self) -> tuple[str, str, str | None]:
        """(root, failure_domain, device_class|None) from the profile
        this plugin was init()ed with."""
        profile = getattr(self, "profile", None) or Profile()
        root = profile.get("crush-root", self.DEFAULT_RULE_ROOT)
        fd = profile.get(
            "crush-failure-domain", self.DEFAULT_RULE_FAILURE_DOMAIN
        )
        dc = profile.get("crush-device-class", "") or None
        return root, fd, dc

    def create_rule(self, name: str, crush_map):
        """Build this profile's erasure rule on ``crush_map`` and
        return it.  Raises ErasureCodeError on unknown root/type/class
        (upstream returns -ENOENT with an error stream)."""
        root, fd, dc = self._rule_profile()
        try:
            return crush_map.make_erasure_rule(name, root, fd, dc)
        except (KeyError, ValueError) as e:
            raise ErasureCodeError(
                f"create_rule {name!r}: {e}"
            ) from e

    # ---- encode: pad -> split -> encode_chunks ----

    def encode_prepare(self, data: np.ndarray) -> dict[int, np.ndarray]:
        """Zero-pad to k*chunk_size and split into k data chunks."""
        blocksize = self.get_chunk_size(len(data))
        chunks: dict[int, np.ndarray] = {}
        for i in range(self.k):
            chunk = np.zeros(blocksize, np.uint8)
            lo = i * blocksize
            hi = min(len(data), (i + 1) * blocksize)
            if hi > lo:
                chunk[: hi - lo] = data[lo:hi]
            chunks[self._chunk_index(i)] = chunk
        for i in range(self.k, self.k + self.m):
            chunks[self._chunk_index(i)] = np.zeros(blocksize, np.uint8)
        return chunks

    def encode(
        self, want_to_encode: set[int], data: bytes | np.ndarray
    ) -> dict[int, np.ndarray]:
        if isinstance(data, (bytes, bytearray)):
            data = np.frombuffer(bytes(data), np.uint8)
        chunks = self.encode_prepare(data)
        self.encode_chunks(chunks)
        return {i: chunks[i] for i in want_to_encode}

    # ---- decode: select k survivors -> decode_chunks ----

    def decode(
        self,
        want_to_read: set[int],
        chunks: dict[int, np.ndarray],
        chunk_size: int,
    ) -> dict[int, np.ndarray]:
        for c in chunks.values():
            if len(c) != chunk_size:
                raise ErasureCodeError("chunk size mismatch")
        if want_to_read <= set(chunks):
            return {i: chunks[i] for i in want_to_read}
        return self.decode_chunks(want_to_read, dict(chunks))

    def decode_concat(self, chunks: dict[int, np.ndarray]) -> bytes:
        """Reassemble the original stripe from data chunks in shard
        order (reference decode_concat)."""
        want = {self._chunk_index(i) for i in range(self.k)}
        chunk_size = len(next(iter(chunks.values())))
        decoded = self.decode(want, chunks, chunk_size)
        return b"".join(
            decoded[self._chunk_index(i)].tobytes() for i in range(self.k)
        )
