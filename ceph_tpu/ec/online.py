"""Online EC write path: device-resident stripe buffer + parity deltas.

The traffic engine classifies write outcomes and models latency, but
until now no write ever encoded a byte.  This module supplies the
data-plane half of the online write path (arXiv:1709.05365's online-EC
result: stripe-buffer hit rate dominates small-write cost on SSD
arrays):

- :class:`StripeBufferState` — an HBM-held stripe cache as one
  fixed-shape pytree: power-of-two-bucketed sets x ways of slots keyed
  by a packed ``(pg, stripe)`` id, each slot holding the stripe's data
  and parity as packed u32 word rows (the XOR-schedule packet layout),
  with per-slot dirty chunk masks and an LRU tick lane.  Being a pure
  pytree it rides ``lax.scan`` carries and checkpoint snapshots
  unchanged.
- :func:`stripe_buffer_step` — one epoch's write batch absorbed on
  device: a ``fori_loop`` does the cache maintenance (lookup, LRU
  victim choice, install-from-backing-store, delta accumulation), then
  ONE vmapped XOR-schedule application turns the accumulated per-slot
  ``Δdata`` into ``Δparity = encode(Δdata)`` for every slot at once.
  Installs and full-stripe writes zero the slot parity and stage the
  whole stripe as a delta-from-zero, so the same fixed program covers
  full-stripe encodes and read-modify-write parity deltas — encoding
  is linear over GF(2), so ``new_parity = old_parity ^ encode(old ^
  new)`` and ``encode(data) = 0 ^ encode(data - 0)`` are the same
  algebra (arXiv:2108.02692's XOR programs, reused verbatim).
- :class:`ParityDeltaEngine` — the host-facing small-write engine:
  for an update footprint (the set of touched data chunks) the parity
  delta is the generator sub-bitmatrix restricted to those chunk
  columns, lowered through :func:`~ceph_tpu.ec.schedule
  .compile_schedule`'s Paar CSE and cached in a
  :class:`~ceph_tpu.ec.schedule.ScheduleCache` per
  ``(codec, footprint)`` — repeated small-write shapes never
  recompile, and the cache's counters/eviction/quarantine machinery
  comes along for free.
- ``dump_stripe_cache`` — the admin-socket hook body: every live
  stripe buffer's occupancy and hit/miss/evict/byte counters, plus
  the ``ec_writepath`` perf component.

Scrub coverage for delta-updated parity (a wrong delta must be caught,
not silently committed) lives in :mod:`ceph_tpu.recovery.scrub`
(:meth:`Scrubber.note_stripe_writes` / ``scrub_stripe_buffer``), built
on :func:`dense_parity_words` — an independent dense GF(2) product, so
a miscompiled or corrupted delta program cannot verify itself.
"""

from __future__ import annotations

import weakref
from dataclasses import dataclass, replace
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ..common.perf_counters import PerfCounters, PerfCountersBuilder, registry
from ..core.hashes import crush_hash32_2
from .schedule import ScheduleCache, XorScheduleEncoder, _xla_apply

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32

#: decorrelate the set-index hash from the routing/payload hashes
_SET_SALT = np.uint32(0xB5297A4D)
#: per-op payload content seed salt
_PAYLOAD_SALT = np.uint32(0x68E31DA4)
#: backing-store stripe content salt (miss installs regenerate from it)
_BASE_SALT = np.uint32(0x1B56C4E9)

#: the per-epoch stripe-buffer output lanes, in row order
WP_LANES = (
    "hits", "misses", "evictions", "delta_writes", "full_writes",
    "delta_words", "full_words", "touched_slots",
)


# ---------------------------------------------------------------------------
# the device-resident stripe buffer


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class StripeBufferState:
    """The HBM-held stripe cache as one fixed-shape pytree.

    ``n_sets`` (a power of two — the set index is a hash masked by
    ``n_sets - 1``) x ``ways`` slots; each slot caches one stripe's
    data and parity as packed u32 word rows in the XOR-schedule packet
    layout (``k*w`` data rows, ``m*w`` parity rows, ``words`` u32 each).
    All leaves are fixed-shape device arrays and every update returns a
    new instance, so the buffer is a valid ``lax.scan`` carry and
    checkpoint payload.
    """

    keys: jnp.ndarray    # i32 [n_sets, ways]  packed stripe key, -1 empty
    data: jnp.ndarray    # u32 [n_sets, ways, k*w, words]
    parity: jnp.ndarray  # u32 [n_sets, ways, m*w, words]
    dirty: jnp.ndarray   # u32 [n_sets, ways]  bitmask over k data chunks
    lru: jnp.ndarray     # i32 [n_sets, ways]  last-access tick, -1 empty
    tick: jnp.ndarray    # i32 []  access counter (the LRU clock)
    totals: jnp.ndarray  # i64 [len(WP_LANES)]  cumulative counters

    def tree_flatten(self):
        return (
            (self.keys, self.data, self.parity, self.dirty, self.lru,
             self.tick, self.totals),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def n_sets(self) -> int:
        return int(self.keys.shape[0])

    @property
    def ways(self) -> int:
        return int(self.keys.shape[1])

    @property
    def words(self) -> int:
        return int(self.data.shape[3])


def empty_stripe_buffer(
    n_sets: int, ways: int, kw: int, mw: int, words: int
) -> StripeBufferState:
    """A cold buffer: all slots empty (``keys == -1``, LRU ``-1`` so
    victim choice fills empties before evicting)."""
    n_sets, ways = int(n_sets), int(ways)
    if n_sets <= 0 or n_sets & (n_sets - 1):
        raise ValueError(f"n_sets must be a power of two, got {n_sets}")
    return StripeBufferState(
        keys=jnp.full((n_sets, ways), -1, I32),
        data=jnp.zeros((n_sets, ways, int(kw), int(words)), U32),
        parity=jnp.zeros((n_sets, ways, int(mw), int(words)), U32),
        dirty=jnp.zeros((n_sets, ways), U32),
        lru=jnp.full((n_sets, ways), -1, I32),
        tick=jnp.zeros((), I32),
        totals=jnp.zeros((len(WP_LANES),), I64),
    )


def _hash_rows(seed, salt: np.uint32, n_rows: int, words: int):
    """Deterministic u32 content rows for one stripe/payload: the
    simulated byte source (and backing store — a re-install after
    eviction regenerates the identical stripe)."""
    grid = jnp.arange(n_rows * words, dtype=U32).reshape(n_rows, words)
    return crush_hash32_2(grid, seed.astype(U32) ^ salt)


def stripe_base_rows(key, kw: int, words: int):
    """The backing store's data rows for stripe ``key`` ([kw, words])."""
    return _hash_rows(key, _BASE_SALT, kw, words)


def payload_rows(seed, kw: int, words: int):
    """One write op's content rows ([kw, words]; small writes mask to
    their chunk's ``w`` rows)."""
    return _hash_rows(seed, _PAYLOAD_SALT, kw, words)


def stripe_buffer_step(
    buf: StripeBufferState,
    steps,
    n_out: int,
    n_bufs: int,
    k: int,
    w: int,
    keys,
    chunks,
    fulls,
    seeds,
    valid,
):
    """Absorb one epoch's fixed-shape write batch; returns the updated
    buffer and the per-epoch counter row (``WP_LANES`` order, i64).

    ``steps`` is the codec's compiled XOR schedule table (device i32
    [n_steps, 2]); ``keys/chunks/fulls/seeds/valid`` are the batch
    lanes (``[B]`` each; invalid lanes are no-ops, so any write count
    up to ``B`` runs through this one program).  Phase 1 is a
    ``fori_loop`` doing cache maintenance and accumulating per-slot
    ``Δdata``; phase 2 XORs ``encode(Δdata)`` into every slot's parity
    with one vmapped schedule application.
    """
    n_sets, ways, kw, words = buf.data.shape
    mw = int(buf.parity.shape[2])
    set_mask = np.uint32(n_sets - 1)
    full_dirty = np.uint32((1 << k) - 1)
    w_words = np.int64(w * words)
    kw_words = np.int64(kw * words)

    def body(i, st):
        (keys_a, data, parity, dirty, lru, tick, ddata, row) = st
        key = keys[i]
        val = valid[i]
        set_i = (
            crush_hash32_2(key.astype(U32), _SET_SALT) & set_mask
        ).astype(I32)
        row_keys = keys_a[set_i]
        eq = row_keys == key
        hit = val & jnp.any(eq)
        victim = jnp.argmin(lru[set_i]).astype(I32)
        way = jnp.where(hit, jnp.argmax(eq).astype(I32), victim)
        install = val & ~hit
        evict = install & (row_keys[way] >= 0)

        # install: slot becomes the backing stripe staged as a
        # delta-from-zero (parity 0, Δdata = data), so phase 2's single
        # encode yields the full-stripe parity
        base = stripe_base_rows(key, kw, words)
        data_s = jnp.where(install, base, data[set_i, way])
        parity_s = jnp.where(
            install, jnp.zeros((mw, words), U32), parity[set_i, way]
        )
        dd_s = jnp.where(install, base, ddata[set_i, way])
        dirty_s = jnp.where(install, jnp.uint32(0), dirty[set_i, way])

        # the write itself: full-stripe replaces the slot (again a
        # delta-from-zero), a small overwrite XORs its chunk's w rows
        full = fulls[i]
        content = payload_rows(seeds[i], kw, words)
        rowsel = ((jnp.arange(kw, dtype=I32) // w) == chunks[i])
        small = jnp.where(rowsel[:, None], content, jnp.uint32(0))
        data_n = jnp.where(full, content, data_s ^ small)
        dd_n = jnp.where(full, content, dd_s ^ small)
        parity_n = jnp.where(
            full, jnp.zeros((mw, words), U32), parity_s
        )
        dirty_n = jnp.where(
            full, full_dirty,
            dirty_s | (jnp.uint32(1) << chunks[i].astype(U32)),
        )

        keep = data[set_i, way]
        data = data.at[set_i, way].set(jnp.where(val, data_n, keep))
        parity = parity.at[set_i, way].set(
            jnp.where(val, parity_n, parity[set_i, way])
        )
        ddata = ddata.at[set_i, way].set(
            jnp.where(val, dd_n, ddata[set_i, way])
        )
        dirty = dirty.at[set_i, way].set(
            jnp.where(val, dirty_n, dirty[set_i, way])
        )
        keys_a = keys_a.at[set_i, way].set(
            jnp.where(val, key, row_keys[way])
        )
        lru = lru.at[set_i, way].set(
            jnp.where(val, tick, lru[set_i, way])
        )
        tick = tick + val.astype(I32)

        # words by ENCODE type: a full write or an install costs a
        # whole-stripe encode; only a small overwrite on a hit is a
        # w-row parity delta
        d_enc = val & ~full & hit
        f_enc = val & (full | ~hit)
        row = row + jnp.stack([
            hit.astype(I64), install.astype(I64), evict.astype(I64),
            (val & ~full).astype(I64), (val & full).astype(I64),
            jnp.where(d_enc, w_words, np.int64(0)),
            jnp.where(f_enc, kw_words, np.int64(0)),
            jnp.int64(0),
        ])
        return (keys_a, data, parity, dirty, lru, tick, ddata, row)

    ddata0 = jnp.zeros_like(buf.data)
    row0 = jnp.zeros((len(WP_LANES),), I64)
    (keys_a, data, parity, dirty, lru, tick, ddata, row) = (
        jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(keys.shape[0]), body,
            (buf.keys, buf.data, buf.parity, buf.dirty, buf.lru,
             buf.tick, ddata0, row0),
        )
    )

    # phase 2: Δparity = encode(Δdata) for every slot in one vmapped
    # schedule application (untouched slots carry Δdata = 0, whose
    # schedule output is 0 — parity unchanged)
    dd_flat = ddata.reshape(n_sets * ways, kw, words)
    dpar = jax.vmap(
        lambda wds: _xla_apply(steps, wds, n_out, n_bufs)
    )(dd_flat)
    parity = parity ^ dpar.reshape(n_sets, ways, mw, words)
    touched = jnp.sum(
        jnp.any(dd_flat != 0, axis=(1, 2)).astype(I64)
    )
    row = row.at[len(WP_LANES) - 1].set(touched)
    out = replace(
        buf, keys=keys_a, data=data, parity=parity, dirty=dirty,
        lru=lru, tick=tick, totals=buf.totals + row,
    )
    return out, row


# ---------------------------------------------------------------------------
# host-facing parity-delta engine (footprint-compiled XOR programs)


def dense_parity_words(bitmatrix: np.ndarray, data_words: np.ndarray):
    """Independent dense GF(2) product over packed u32 word rows:
    ``[mw, kw] x [kw, NW] -> [mw, NW]``.  The scrub re-encode reference
    — no shared code with the schedule compiler, so a wrong delta
    program cannot verify itself."""
    bm = (np.asarray(bitmatrix) & 1).astype(bool)
    sel = np.where(
        bm[:, :, None], np.asarray(data_words, np.uint32)[None, :, :],
        np.uint32(0),
    )
    return np.bitwise_xor.reduce(sel, axis=1)


class ParityDeltaEngine:
    """Read-modify-write parity deltas for one codec bitmatrix.

    Encoding is linear over GF(2), so overwriting chunks ``F`` turns
    the parity update into ``Δparity = encode_F(old_F ^ new_F)`` where
    ``encode_F`` is the generator bitmatrix restricted to ``F``'s
    chunk columns.  Each footprint's program lowers through the Paar
    CSE compiler once and is cached per ``(codec, footprint)`` in a
    :class:`~ceph_tpu.ec.schedule.ScheduleCache` — repeated small-write
    shapes never recompile, and cache hits/evictions land in the
    shared ``ec_schedule`` counters.
    """

    def __init__(
        self,
        bitmatrix: np.ndarray,
        w: int = 8,
        packetsize: int = 8,
        cache: ScheduleCache | None = None,
        name: str = "writepath",
    ):
        self.bitmatrix = np.asarray(bitmatrix, np.uint8) & 1
        self.w = int(w)
        self.packetsize = int(packetsize)
        self.mw, self.kw = self.bitmatrix.shape
        if self.kw % self.w or self.mw % self.w:
            raise ValueError(
                f"bitmatrix {self.bitmatrix.shape} not a multiple of "
                f"w={self.w}"
            )
        self.k = self.kw // self.w
        self.m = self.mw // self.w
        # stable cache key half: the generator's content fingerprint
        from ..recovery.scrub import crc32c

        self.codec_id = (
            self.k, self.m, self.w,
            crc32c(np.ascontiguousarray(self.bitmatrix).reshape(-1)),
        )
        self.cache = cache if cache is not None else ScheduleCache(
            name=name
        )

    def _footprint(self, footprint) -> tuple[int, ...]:
        fp = tuple(sorted({int(c) for c in footprint}))
        if not fp or fp[0] < 0 or fp[-1] >= self.k:
            raise ValueError(
                f"footprint {fp} out of range for k={self.k}"
            )
        return fp

    def delta_bitmatrix(self, footprint) -> np.ndarray:
        """The generator sub-bitmatrix for an update footprint: the
        column blocks of the touched data chunks."""
        fp = self._footprint(footprint)
        cols = np.concatenate(
            [np.arange(c * self.w, (c + 1) * self.w) for c in fp]
        )
        return np.ascontiguousarray(self.bitmatrix[:, cols])

    def encoder_for(self, footprint) -> XorScheduleEncoder:
        """The compiled delta program for one footprint (cached)."""
        fp = self._footprint(footprint)
        return self.cache.get(
            ("delta", self.codec_id, fp),
            lambda: XorScheduleEncoder(
                self.delta_bitmatrix(fp), layout="packet",
                w=self.w, packetsize=self.packetsize,
            ),
        )

    def full_encoder(self) -> XorScheduleEncoder:
        """The full-stripe encode program (cached once per codec)."""
        return self.cache.get(
            ("full", self.codec_id),
            lambda: XorScheduleEncoder(
                self.bitmatrix, layout="packet",
                w=self.w, packetsize=self.packetsize,
            ),
        )

    def encode(self, data: np.ndarray) -> np.ndarray:
        """Full-stripe parity ``[k, S] u8 -> [m, S] u8`` through the
        schedule path (the batched full-stripe write engine)."""
        return self.full_encoder().encode(data)

    def dense_parity(self, data: np.ndarray) -> np.ndarray:
        """Dense reference parity (independent execution path — the
        bit-equality gate's and scrub's comparison side)."""
        from .backend import BitmatrixEncoder

        return BitmatrixEncoder(
            self.bitmatrix, self.packetsize, self.w
        ).encode(data)

    def apply_delta(
        self, parity: np.ndarray, footprint, old_chunks: np.ndarray,
        new_chunks: np.ndarray,
    ) -> np.ndarray:
        """One read-modify-write: ``parity ^ encode_F(old ^ new)``.

        ``old_chunks``/``new_chunks`` are ``[len(F), S] u8`` in
        footprint order; returns the ``[m, S]`` updated parity."""
        fp = self._footprint(footprint)
        old = np.asarray(old_chunks, np.uint8)
        new = np.asarray(new_chunks, np.uint8)
        if old.shape != new.shape or old.shape[0] != len(fp):
            raise ValueError(
                f"delta chunks {old.shape}/{new.shape} do not match "
                f"footprint {fp}"
            )
        dparity = self.encoder_for(fp).encode(old ^ new)
        return np.asarray(parity, np.uint8) ^ dparity

    def pc_inc(self, counters: "PerfCounters", row) -> None:
        """Fold one epoch row (``WP_LANES`` order) into the
        ``ec_writepath`` perf component."""
        vals = [int(v) for v in np.asarray(row).reshape(-1)]
        for lane, v in zip(WP_LANES, vals):
            name = _COUNTER_OF.get(lane)
            if name is not None and v:
                counters.inc(name, v)


# ---------------------------------------------------------------------------
# observability: counters + the dump_stripe_cache admin hook


_COUNTER_OF = {
    "hits": "stripe_hits",
    "misses": "stripe_misses",
    "evictions": "stripe_evictions",
    "delta_writes": "delta_writes",
    "full_writes": "full_writes",
    "delta_words": "delta_words",
    "full_words": "full_words",
}


def _build_counters() -> PerfCounters:
    return (
        PerfCountersBuilder("ec_writepath")
        .add_u64_counter(
            "stripe_hits", "write ops served from a resident stripe"
        )
        .add_u64_counter(
            "stripe_misses",
            "write ops that installed their stripe from the backing "
            "store",
        )
        .add_u64_counter(
            "stripe_evictions",
            "resident stripes displaced by an LRU victim choice",
        )
        .add_u64_counter(
            "delta_writes", "small overwrites absorbed as parity deltas"
        )
        .add_u64_counter(
            "full_writes", "full-stripe writes batched through encode"
        )
        .add_u64_counter(
            "delta_words",
            "u32 words encoded through footprint delta programs",
        )
        .add_u64_counter(
            "full_words",
            "u32 words encoded as whole-stripe parity (installs + "
            "full-stripe writes)",
        )
        .create_perf_counters()
    )


def writepath_counters() -> PerfCounters:
    """The process-wide ``ec_writepath`` perf-counter component."""
    return registry().get("ec_writepath") or _build_counters()


# every live stripe buffer owner, for the dump_stripe_cache admin hook
_LIVE_STRIPE_CACHES: weakref.WeakSet = weakref.WeakSet()


def register_stripe_cache(owner) -> None:
    """Self-register an object exposing ``dump_stripe_cache() ->
    dict`` (the :class:`~ceph_tpu.workload.writepath.WritepathDriver`
    does this on construction)."""
    _LIVE_STRIPE_CACHES.add(owner)


def summarize_buffer(buf: StripeBufferState) -> dict:
    """Host summary of one buffer's occupancy and counters (the admin
    hook payload; a cold-path host pull, never inside the scan)."""
    keys, dirty, totals = jax.device_get(
        (buf.keys, buf.dirty, buf.totals)
    )
    totals = {
        lane: int(v) for lane, v in zip(WP_LANES, totals.reshape(-1))
    }
    lookups = totals["hits"] + totals["misses"]
    return {
        "n_sets": int(keys.shape[0]),
        "ways": int(keys.shape[1]),
        "occupied": int((keys >= 0).sum()),
        "dirty_slots": int((dirty != 0).sum()),
        "hit_rate": (
            round(totals["hits"] / lookups, 4) if lookups else 0.0
        ),
        "delta_bytes": 4 * totals["delta_words"],
        "full_bytes": 4 * totals["full_words"],
        **totals,
    }


def dump_stripe_cache() -> dict:
    """Admin-socket hook body: every live stripe buffer plus the
    aggregate ``ec_writepath`` counters."""
    return {
        "buffers": sorted(
            (o.dump_stripe_cache() for o in _LIVE_STRIPE_CACHES),
            key=lambda d: str(d.get("name", "")),
        ),
        "counters": writepath_counters().dump(),
    }
