"""Pallas TPU kernels for GF(2^8) byte-table operations.

Round-3 silicon profiling: XLA lowers per-lane table gathers at
~10 ns/lane on the chip regardless of table size, which makes every
``jnp.take``-based GF(2^8) path (TableEncoder's log-table multiply,
CLAY's coupled-pair transforms) gather-bound by 2-3 orders of
magnitude.  The cure is the TPU's in-register table unit
(``tpu.dynamic_gather``), reachable only through Pallas and only for
128-wide lane-resident tables — so 256-entry GF tables are split into
two 128-entry halves and selected (see pallas_straw2.py for the same
trick on crush_ln's LUTs).

Two primitives:

- :func:`byte_lut` — ``table[x]`` for u8 arrays, any shape.
- :func:`matrix_encode` — ``coding[j] = XOR_i mul_table[M[j,i]][data[i]]``,
  the whole GF matrix-vector product over a chunk batch in one kernel
  (TableEncoder's inner loop with the m*k byte lookups fused).

Both fall back to the jnp gather path off-TPU (tests force the kernels
through interpret mode); results are bit-identical by construction and
test-enforced.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from .. import enable_x64 as _enable_x64

SUBLANES = 256
TILE = SUBLANES * 128  # u32 words per grid step


def _pad_words(x32, tile):
    n = x32.shape[0]
    npad = (n + tile - 1) // tile * tile
    if npad != n:
        x32 = jnp.pad(x32, (0, npad - n))
    return x32, n


def _tbl_lanes(table: np.ndarray) -> np.ndarray:
    """[256] u8 -> [2, 128] u32 lane-resident halves."""
    t = np.asarray(table, np.uint8).astype(np.uint32)
    return t.reshape(2, 128)


def _lut256(tbl_ref, row0: int, idx):
    """table[idx] for idx in [0,256): two 128-lane gathers + select.
    ``tbl_ref`` rows [row0, row0+1] hold the table halves."""
    hi = idx >= np.uint32(128)
    li = (idx & np.uint32(127)).astype(jnp.int32)
    lo_v = jnp.take_along_axis(
        jnp.broadcast_to(tbl_ref[row0:row0 + 1, :], li.shape), li, axis=1)
    hi_v = jnp.take_along_axis(
        jnp.broadcast_to(tbl_ref[row0 + 1:row0 + 2, :], li.shape), li, axis=1)
    return jnp.where(hi, hi_v, lo_v)


def _word_lut(tbl_ref, row0: int, w):
    """Apply a 256-entry byte table to all 4 bytes of u32 words."""
    out = jnp.zeros_like(w)
    for b in range(4):
        idx = (w >> np.uint32(8 * b)) & np.uint32(0xFF)
        out = out | (_lut256(tbl_ref, row0, idx) << np.uint32(8 * b))
    return out


def _byte_lut_kernel(x_ref, tbl_ref, o_ref):
    o_ref[:, :] = _word_lut(tbl_ref, 0, x_ref[:, :])


def _byte_lut_call(x32, tbl, interpret: bool):
    with _enable_x64(False):
        return _byte_lut_jit(x32, tbl, interpret)


@partial(jax.jit, static_argnums=(2,))
def _byte_lut_jit(x32, tbl, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = x32.shape[0]
    rows = n // 128
    sub = min(SUBLANES, rows)  # small inputs: shrink the tile
    bs = pl.BlockSpec((sub, 128), lambda i: (i, 0),
                      memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _byte_lut_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.uint32),
        grid=(rows // sub,),
        in_specs=[bs, pl.BlockSpec((2, 128), lambda i: (0, 0),
                                   memory_space=pltpu.VMEM)],
        out_specs=bs,
        interpret=interpret,
    )(x32.reshape(rows, 128), tbl).reshape(n)


def byte_lut(x, table, interpret: bool | None = None):
    """``table[x]`` for a u8 array of any shape (device-fast on TPU).

    ``table``: 256-entry u8 (numpy or device).  Bit-identical to
    ``jnp.take(table, x)``; pads internally to the tile size.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    x = jnp.asarray(x, jnp.uint8)
    shape = x.shape
    flat = x.reshape(-1)
    n8 = flat.shape[0]
    if n8 == 0:
        return x
    # pack to u32 words (4 bytes/lane); pad bytes to word multiple
    if n8 % 4:
        flat = jnp.pad(flat, (0, 4 - n8 % 4))
    words = jax.lax.bitcast_convert_type(
        flat.reshape(-1, 4), jnp.uint32).reshape(-1)
    rows_needed = (words.shape[0] + 127) // 128
    gran = min(SUBLANES, rows_needed) * 128
    words, nw = _pad_words(words, gran)
    tbl = jnp.asarray(_tbl_lanes(np.asarray(table)))
    out = _byte_lut_call(words, tbl, interpret)[:nw]
    ob = jax.lax.bitcast_convert_type(
        out.reshape(-1, 1), jnp.uint8).reshape(-1)[:n8]
    return ob.reshape(shape)


# ---------------------------------------------------------------------------
# Fused GF matrix encode: coding[j] = XOR_i mul(M[j,i], data[i])
# ---------------------------------------------------------------------------


def _make_matrix_kernel(m: int, k: int):
    def kern(d_ref, tbl_ref, o_ref):
        for j in range(m):
            acc = jnp.zeros_like(d_ref[0])
            for i in range(k):
                acc = acc ^ _word_lut(tbl_ref, 2 * (j * k + i), d_ref[i])
            o_ref[j] = acc
    return kern


def _matrix_call(d32, tbl, m: int, interpret: bool):
    with _enable_x64(False):
        return _matrix_jit(d32, tbl, m, interpret)


@partial(jax.jit, static_argnums=(2, 3))
def _matrix_jit(d32, tbl, m, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, n = d32.shape
    rows = n // 128
    sub = min(SUBLANES, rows)
    return pl.pallas_call(
        _make_matrix_kernel(m, k),
        out_shape=jax.ShapeDtypeStruct((m, rows, 128), jnp.uint32),
        grid=(rows // sub,),
        in_specs=[
            pl.BlockSpec((k, sub, 128), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(tbl.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, sub, 128), lambda i: (0, i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(d32.reshape(k, rows, 128), tbl).reshape(m, n)


def matrix_encode(matrix, data, interpret: bool | None = None):
    """GF(2^8) ``[m, k] x [k, S] -> [m, S]`` via fused byte-table kernel.

    Bit-identical to the log-table path (``gf.matrix_encode``); ``S``
    padded internally.  Used by TableEncoder's device path on TPU.
    """
    from . import gf

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    M = np.asarray(matrix, np.uint8)
    m, k = M.shape
    mt = gf.mul_table()
    tbl = np.concatenate(
        [_tbl_lanes(mt[M[j, i]]) for j in range(m) for i in range(k)], axis=0
    )  # [2*m*k, 128]
    d = jnp.asarray(data, jnp.uint8)
    S = d.shape[1]
    if S == 0:
        return jnp.zeros((m, 0), jnp.uint8)
    pad8 = (4 - S % 4) % 4
    if pad8:
        d = jnp.pad(d, ((0, 0), (0, pad8)))
    words = jax.lax.bitcast_convert_type(
        d.reshape(k, -1, 4), jnp.uint32)  # [k, S/4]
    nw = words.shape[1]
    npad = (nw + TILE - 1) // TILE * TILE
    # small inputs: shrink the tile rather than pad 32x
    if npad != nw:
        rows_needed = (nw + 127) // 128
        sub = min(SUBLANES, rows_needed)
        npad = (nw + sub * 128 - 1) // (sub * 128) * (sub * 128)
        words = jnp.pad(words, ((0, 0), (0, npad - nw)))
    out = _matrix_call(words, jnp.asarray(tbl), m, interpret)[:, :nw]
    ob = jax.lax.bitcast_convert_type(
        out.reshape(m, -1, 1), jnp.uint8).reshape(m, -1)
    return ob[:, :S]
