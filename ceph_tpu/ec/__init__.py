from .interface import ErasureCode, ErasureCodeError, ErasureCodeInterface, Profile
from .registry import ErasureCodePluginRegistry, create, register_plugin

__all__ = [
    "ErasureCode",
    "ErasureCodeError",
    "ErasureCodeInterface",
    "Profile",
    "ErasureCodePluginRegistry",
    "create",
    "register_plugin",
]
