"""Pallas TPU kernels for GF(2) erasure coding.

The bitmatrix encode (SURVEY.md §2.2.3: XOR schedules over packet
regions, upstream ``jerasure_schedule_encode``) is algebraically
``C = B ⊙ D`` over GF(2) where B's entries select data packet-rows to
XOR.  The XLA path (:class:`~ceph_tpu.ec.backend.BitmatrixEncoder`)
bit-unpacks bytes to int8 planes and rides the MXU; that costs an 8x
materialization in HBM and leaves the MXU underutilized at these
shapes (contraction dim 8k ~ 64, output dim 8m ~ 24).

This kernel instead keeps bytes packed as u32 words and XOR-accumulates
selected rows on the VPU entirely in VMEM, one pass over the data:
traffic = read D + write C (the optimum), ~3 vector ops per data byte.
B is precompiled to full-width masks so selection is an AND.

Exposed as :func:`xor_bitmatrix_encode`; falls back to the XLA path on
non-TPU backends (Mosaic interpret mode is used in tests).

:func:`schedule_apply` is the second kernel: it interprets a compiled
XOR schedule (:mod:`ceph_tpu.ec.schedule` — CSE-shrunk step table) as
one ``fori_loop`` scan over SMEM steps with VMEM scratch accumulator
rows, so a whole pattern-group decode is a single launch whose XOR
count the compiler already minimized.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from .. import enable_x64 as _enable_x64

W = 8
LANES = 128  # u32 lane tile


def _pad_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _kernel(masks_ref, d_ref, out_ref):
    """One N-tile: out[mw, TN] = XOR_s (d[s, TN] & mask[mw, s]).

    ``masks_ref`` is [KW, MW, 1]: the XOR-select loop indexes the
    *untiled* leading dim, so Mosaic never sees a dynamic lane-dim
    offset (a [MW, KW] layout lowers ``masks[:, s]`` to a lane-strided
    ``vector.load`` that real TPUs reject with an alignment error —
    caught on first silicon run, round 3).  The [MW, 1] slice is
    already sublane-oriented and broadcasts across lanes for free.
    """
    kw = d_ref.shape[0]
    acc = jnp.zeros(out_ref.shape, jnp.uint32)
    # Static Python unroll (kw <= 8*k, small): no loop-carried scalars
    # for Mosaic to legalize (x64 mode made fori_loop bounds i64, which
    # it rejects) and every load has a static index.
    for s in range(kw):
        row = d_ref[s, :]  # [TN] u32
        sel = masks_ref[s]  # [MW, 1] u32 (0 or 0xffffffff)
        acc = acc ^ (row[None, :] & sel)
    out_ref[:, :] = acc


def _encode_padded(masks, d_words, interpret=False):
    """masks [KW, MWpad, 1] u32; d_words [KW, NW] u32 -> [MWpad, NW] u32.

    Traced with x64 scoped off: x64 mode leaks i64 into the BlockSpec
    index maps, which Mosaic refuses to legalize on real TPUs
    ("func.return (i64,i64,i64)", first silicon run).  Everything here
    is u32, so the scope changes no dtypes.
    """
    with _enable_x64(False):
        return _encode_padded_jit(masks, d_words, interpret=interpret)


@partial(jax.jit, static_argnames=("interpret",))
def _encode_padded_jit(masks, d_words, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kw, mw_pad, _ = masks.shape
    nw = d_words.shape[1]
    tile = LANES * 4  # words per grid step
    if nw % tile:
        # never collapse to one whole-array block: that blows VMEM on
        # large chunks (round-2 review finding); callers pad (encode()
        # always does) so this only fires on misuse
        raise ValueError(f"word count {nw} must be a multiple of {tile}")
    grid = (nw // tile,)
    tn = tile
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((mw_pad, nw), jnp.uint32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((kw, mw_pad, 1), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kw, tn), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((mw_pad, tn), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(masks, d_words)


def _schedule_kernel(steps_ref, d_ref, out_ref, scratch_ref):
    """One N-tile of the XOR-schedule interpreter.

    ``steps_ref`` [n_steps, 2] i32 lives in SMEM (scalar loads drive
    control flow); ``scratch_ref`` [n_bufs, TN] u32 VMEM holds the
    schedule's buffers ``[inputs | outputs | derived]``.  Step (dst,
    src) is ``scratch[dst] ^= scratch[src]`` — the dynamic index is on
    the SUBLANE dim only (``pl.ds`` over rows), the pattern Mosaic
    accepts; a lane-dim dynamic offset is what the encode kernel's mask
    layout already dodges (see :func:`_kernel`).
    """
    from jax.experimental import pallas as pl

    n_in = d_ref.shape[0]
    n_out = out_ref.shape[0]
    n_bufs, tn = scratch_ref.shape
    scratch_ref[0:n_in, :] = d_ref[:, :]
    scratch_ref[n_in:, :] = jnp.zeros((n_bufs - n_in, tn), jnp.uint32)

    def body(i, carry):
        dst = steps_ref[i, 0]
        src = steps_ref[i, 1]
        scratch_ref[pl.ds(dst, 1), :] = (
            scratch_ref[pl.ds(dst, 1), :] ^ scratch_ref[pl.ds(src, 1), :]
        )
        return carry

    jax.lax.fori_loop(
        jnp.int32(0), jnp.int32(steps_ref.shape[0]), body, jnp.int32(0)
    )
    out_ref[:, :] = scratch_ref[n_in:n_in + n_out, :]


def schedule_apply(steps, d_words, n_out: int, n_bufs: int,
                   interpret: bool = False, device=None):
    """Run a compiled XOR schedule on device.

    ``steps`` [n_steps, 2] i32; ``d_words`` [n_in, NW] u32 — the word
    axis is padded here to the kernel's LANES*4 tile (callers trim with
    the layout's word count).  Traced with x64 scoped off like
    :func:`_encode_padded` (i64 BlockSpec index maps are a Mosaic
    rejection).  Returns the in-flight [n_out, NWpad] u32 array.
    """
    d_words = np.asarray(d_words)
    nw = d_words.shape[1]
    nw_pad = _pad_to(max(nw, LANES * 4), LANES * 4)
    if nw_pad != nw:
        d_words = np.pad(d_words, ((0, 0), (0, nw_pad - nw)))
    if device is not None:
        d_words = jax.device_put(d_words, device)
    with _enable_x64(False):
        return _schedule_padded_jit(
            jnp.asarray(steps), jnp.asarray(d_words),
            n_out=n_out, n_bufs=n_bufs, interpret=interpret,
        )


@partial(jax.jit, static_argnames=("n_out", "n_bufs", "interpret"))
def _schedule_padded_jit(steps, d_words, n_out, n_bufs, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_in, nw = d_words.shape
    n_steps = steps.shape[0]
    tile = LANES * 4
    if nw % tile:
        raise ValueError(f"word count {nw} must be a multiple of {tile}")
    grid = (nw // tile,)
    return pl.pallas_call(
        _schedule_kernel,
        out_shape=jax.ShapeDtypeStruct((n_out, nw), jnp.uint32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((n_steps, 2), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((n_in, tile), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((n_out, tile), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((n_bufs, tile), jnp.uint32)],
        interpret=interpret,
    )(steps, d_words)


class PallasBitmatrixEncoder:
    """Drop-in engine for BitmatrixEncoder's inner product (same packet
    layout contract as ``gfref_bitmatrix_encode``).  Packet sizes that
    are not a word multiple are handled by tail-padding each packet to
    a whole u32 in :meth:`_pack_words` (XOR of zero-padded packets is
    the zero-padded XOR) and trimming the tail on output."""

    def __init__(self, bitmatrix: np.ndarray, packetsize: int,
                 interpret: bool | None = None):
        self.bitmatrix = np.asarray(bitmatrix, np.uint8)
        self.mw, self.kw = self.bitmatrix.shape
        self.k, self.m = self.kw // W, self.mw // W
        self.packetsize = packetsize
        self.mw_pad = _pad_to(self.mw, 8)
        masks = np.zeros((self.kw, self.mw_pad, 1), np.uint32)
        masks[:, : self.mw, 0] = np.where(
            self.bitmatrix != 0, 0xFFFFFFFF, 0
        ).T
        self._masks = masks
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self._interpret = interpret

    def _pack_words(self, data: np.ndarray) -> tuple[np.ndarray, int]:
        """Packet-interleave [k, S] u8 into the kernel's padded
        [KW, NWpad] u32 layout; returns (words, unpadded word count).
        The single source of the kernel's input contract — benches
        must use this, not a re-implementation."""
        k, p = self.k, self.packetsize
        size = data.shape[1]
        group = W * p
        if size % group:
            raise ValueError(f"chunk size {size} % {group} != 0")
        g = size // group
        pb = _pad_to(p, 4)
        d = np.ascontiguousarray(data).reshape(k, g, W, p)
        d = d.transpose(0, 2, 1, 3).reshape(k * W, g, p)
        if pb != p:
            d = np.pad(d, ((0, 0), (0, 0), (0, pb - p)))
        d_words = np.ascontiguousarray(d).view(np.uint32)
        d_words = d_words.reshape(k * W, g * (pb // 4))
        nw = d_words.shape[1]
        nw_pad = _pad_to(max(nw, LANES * 4), LANES * 4)
        if nw_pad != nw:
            d_words = np.pad(d_words, ((0, 0), (0, nw_pad - nw)))
        return d_words, nw

    def encode(self, data: np.ndarray) -> np.ndarray:
        """data [k, S] u8 -> coding [m, S] u8 (packet-interleaved)."""
        k, m, p = self.k, self.m, self.packetsize
        size = data.shape[1]
        g = size // (W * p)
        pb = _pad_to(p, 4)
        d_words, nw = self._pack_words(data)
        out = np.asarray(
            _encode_padded(
                jnp.asarray(self._masks), jnp.asarray(d_words),
                interpret=self._interpret,
            )
        )[: self.mw, :nw]
        c = out.view(np.uint8).reshape(m, W, g, pb)[..., :p]
        c = c.transpose(0, 2, 1, 3)
        return np.ascontiguousarray(c.reshape(m, size))
