"""Device-resident cluster state: one pytree, resident across epochs.

Every epoch-loop consumer so far (peering, the traffic router, the
PG-state classifier, the liveness detector) kept its *own* slice of
cluster state on device and re-uploaded the rest from the host
``OSDMap`` each epoch via :func:`~ceph_tpu.osdmap.mapping
.build_pool_state` — an O(cluster) host walk per epoch that caps the
simulator's epoch rate and the map size it can afford.  This module
unifies those slices into one :class:`ClusterState` pytree that stays
resident in HBM across epochs:

- the pool-mapping tables (a nested
  :class:`~ceph_tpu.osdmap.mapping.PoolMapState`: bucket weights,
  up/exists bits, affinity, upmap/temp overrides),
- per-OSD liveness lanes (the :mod:`ceph_tpu.recovery.liveness`
  heartbeat state plus the host-authoritative suppression/out bits,
  promoted to device lanes),
- per-PG peering outputs (up/acting tables, primaries, flags, survivor
  bitmasks, alive counts),
- the PG-state histogram and aux counts,
- optional checksum-table refs (the scrubber's stored CRC32C table),
- scalar clocks/cursors (map epoch, virtual now, last liveness tick,
  chaos event-tape cursor, epoch-loop step).

OSDMap :class:`~ceph_tpu.osdmap.map.Incremental` deltas apply as ONE
compiled fixed-shape scatter (:func:`apply_incremental`) — O(delta)
work instead of the O(cluster) ``build_pool_state`` recompute — with
the pad width bucketed to powers of two so delta size never recompiles.
Structural edits (``new_max_osd``, pool changes, upmap/temp rewrites)
change shapes or dict layouts and still go through
:meth:`ClusterState.from_osdmap`; the compiled path covers the
hot-loop deltas chaos and the failure detector actually emit
(state xors + reweights + affinity).

The compiled epoch superstep (:mod:`ceph_tpu.recovery.superstep`)
carries a :class:`ClusterState` through ``lax.scan``; the staged
differential-reference path advances the identical pytree one jitted
piece at a time.

Fleets
------

:func:`stack_states` stacks N independent ``ClusterState`` pytrees
along a new leading *fleet* axis — every leaf gains ``[fleet, ...]``
and the result is still a ``ClusterState`` (the vmapped scenario-fleet
superstep's carry, :mod:`ceph_tpu.recovery.fleet`).  The batched twin
of the O(delta) scatter is :func:`apply_incremental_fleet`: one
compiled vmapped scatter applies a *per-cluster* Incremental to every
fleet member, with the delta pads bucketed to powers of two across the
fleet so neither fleet size nor delta size recompiles.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace

import numpy as np

import jax
import jax.numpy as jnp

from ..crush.map import ITEM_NONE
from ..osdmap.map import Incremental, OSDMap
from ..osdmap.mapping import PoolMapState, build_pool_state

I32 = jnp.int32
U32 = jnp.uint32
F32 = jnp.float32
F64 = jnp.float64

#: reporter count meaning "always enough reporters" (the
#: LivenessDetector default before peering adjacency is known)
ALWAYS_REPORTED = 1 << 16

#: fields of one Incremental the compiled scatter path cannot express
#: without a shape change or a dict rewrite — they route through
#: ``from_osdmap`` instead
_STRUCTURAL_FIELDS = (
    "new_pg_upmap", "old_pg_upmap", "new_pg_upmap_items",
    "old_pg_upmap_items", "new_pg_temp", "new_primary_temp", "new_pools",
)


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class ClusterState:
    """The whole cluster's dynamic state as one device-resident pytree.

    All leaves are fixed-shape device arrays; every update path
    (compiled incrementals, the epoch superstep, the staged reference)
    returns a new instance via :func:`dataclasses.replace` — the pytree
    is immutable, so it can be a ``lax.scan`` carry.
    """

    # -- pool mapping (nested pytree; the CRUSH program's traced state)
    pool: PoolMapState

    # -- per-OSD liveness lanes (heartbeat_step's eight lanes plus the
    #    bits the host detector kept authoritative)
    last_ack: jnp.ndarray      # f32 [n_osd]
    laggy: jnp.ndarray         # f32 [n_osd]
    markdowns: jnp.ndarray     # f32 [n_osd]
    down: jnp.ndarray          # bool [n_osd]  detector-marked down
    down_since: jnp.ndarray    # f32 [n_osd]
    suppressed: jnp.ndarray    # bool [n_osd]  netsplit: heartbeats cut
    slow: jnp.ndarray          # bool [n_osd]  slow: acks late
    out: jnp.ndarray           # bool [n_osd]  auto-out bookkeeping
    reporters: jnp.ndarray     # i32 [n_osd]  failure-reporter pool

    # -- per-PG peering tables (the fused pipeline's outputs)
    up: jnp.ndarray            # i32 [pg_num, size]  ITEM_NONE padded
    up_primary: jnp.ndarray    # i32 [pg_num]
    acting: jnp.ndarray        # i32 [pg_num, size]
    acting_primary: jnp.ndarray  # i32 [pg_num]
    flags: jnp.ndarray         # i32 [pg_num]  PG_STATE_* bits
    survivor_mask: jnp.ndarray  # u32 [pg_num]
    n_alive: jnp.ndarray       # i32 [pg_num]

    # -- cluster-wide observability
    pg_hist: jnp.ndarray       # i32 [N_STATES]
    pg_aux: jnp.ndarray        # i32 [2]  degraded_slots, misplaced

    # -- checksum table ref (the scrubber's stored CRC32C table; None
    #    when no store is attached — consistently absent or present
    #    across a run, like any optional pytree leaf)
    checksums: jnp.ndarray | None  # u32 [pg_num, n_shards] | None

    # -- scalars
    epoch: jnp.ndarray         # i32 []  map epoch
    now: jnp.ndarray           # f64 []  virtual time
    last_tick: jnp.ndarray     # f64 []  last non-idle liveness tick
    tape_cursor: jnp.ndarray   # i32 []  chaos event-tape position
    step: jnp.ndarray          # i32 []  epoch-loop step index

    def tree_flatten(self):
        return (
            (
                self.pool,
                self.last_ack, self.laggy, self.markdowns, self.down,
                self.down_since, self.suppressed, self.slow, self.out,
                self.reporters,
                self.up, self.up_primary, self.acting,
                self.acting_primary, self.flags, self.survivor_mask,
                self.n_alive,
                self.pg_hist, self.pg_aux, self.checksums,
                self.epoch, self.now, self.last_tick, self.tape_cursor,
                self.step,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # -- constructors --------------------------------------------------

    @classmethod
    def from_osdmap(
        cls,
        m: OSDMap,
        pool_id: int | None = None,
        *,
        max_items: int = 8,
        now: float = 0.0,
        reporters: np.ndarray | None = None,
        checksums: np.ndarray | None = None,
    ) -> "ClusterState":
        """Compile a host OSDMap into the resident pytree (the cold
        path; epoch deltas after this go through
        :func:`apply_incremental` or the superstep's event tape)."""
        # deferred: obs.pg_states pulls in recovery.peering, whose
        # package __init__ loads the superstep module, which builds on
        # this one — a module-level import here would close that cycle
        from ..obs.pg_states import N_STATES

        pool = m.pools[min(m.pools) if pool_id is None else pool_id]
        pool_state = build_pool_state(m, pool, max_items)
        n = int(pool_state.osd_weight.shape[0])
        pg_num = int(pool.pg_num)
        size = int(pool.size)
        if reporters is None:
            rep = np.full(n, ALWAYS_REPORTED, np.int32)
        else:
            rep = np.asarray(reporters, np.int32)
            if rep.shape != (n,):
                raise ValueError(
                    f"reporters shape {rep.shape} != ({n},)"
                )
        return cls(
            pool=pool_state,
            last_ack=jnp.full((n,), float(now), F32),
            laggy=jnp.zeros((n,), F32),
            markdowns=jnp.zeros((n,), F32),
            down=jnp.zeros((n,), bool),
            down_since=jnp.zeros((n,), F32),
            suppressed=jnp.zeros((n,), bool),
            slow=jnp.zeros((n,), bool),
            out=jnp.zeros((n,), bool),
            reporters=jnp.asarray(rep),
            up=jnp.full((pg_num, size), ITEM_NONE, I32),
            up_primary=jnp.full((pg_num,), -1, I32),
            acting=jnp.full((pg_num, size), ITEM_NONE, I32),
            acting_primary=jnp.full((pg_num,), -1, I32),
            flags=jnp.zeros((pg_num,), I32),
            survivor_mask=jnp.zeros((pg_num,), U32),
            n_alive=jnp.zeros((pg_num,), I32),
            pg_hist=jnp.zeros((N_STATES,), I32),
            pg_aux=jnp.zeros((2,), I32),
            checksums=(
                None if checksums is None
                else jnp.asarray(checksums, U32)
            ),
            epoch=jnp.int32(m.epoch),
            now=jnp.float64(now),
            last_tick=jnp.float64(now),
            tape_cursor=jnp.int32(0),
            step=jnp.int32(0),
        )

    @property
    def n_osds(self) -> int:
        return int(self.pool.osd_weight.shape[0])

    @property
    def pg_num(self) -> int:
        return int(self.up.shape[0])


# ---------------------------------------------------------------------------
# view deltas: what one resident view advanced past another


@dataclass(frozen=True)
class ViewDelta:
    """Host-side summary of what separates two views of the SAME
    geometry — the audit record the reconcile layer journals when a
    stalled rank replays its missed window (the "delta tape": the
    step/tape-cursor span below, driven back through the same
    deterministic scan).  This is a *description* of the delta, not a
    patch: replaying the missed steps reproduces the target view
    bit-exactly, so no state injection is ever applied."""

    epoch_from: int
    epoch_to: int
    step_from: int
    step_to: int
    tape_cursor_from: int
    tape_cursor_to: int
    n_up_changed: int      # osd_up lanes that differ
    n_down_changed: int    # detector down bits that differ
    n_out_changed: int     # out bookkeeping bits that differ
    n_pgs_remapped: int    # PGs whose acting set differs

    @property
    def n_steps(self) -> int:
        return self.step_to - self.step_from

    @property
    def n_tape_rows(self) -> int:
        return self.tape_cursor_to - self.tape_cursor_from

    def to_json(self) -> dict:
        return {
            "epoch_from": self.epoch_from, "epoch_to": self.epoch_to,
            "step_from": self.step_from, "step_to": self.step_to,
            "tape_rows": self.n_tape_rows, "n_steps": self.n_steps,
            "n_up_changed": self.n_up_changed,
            "n_down_changed": self.n_down_changed,
            "n_out_changed": self.n_out_changed,
            "n_pgs_remapped": self.n_pgs_remapped,
        }


def view_delta(old: ClusterState, new: ClusterState) -> ViewDelta:
    """Diff two same-geometry views into a :class:`ViewDelta` (one
    host pull per view; a between-rounds seam, never in-scan)."""
    o, n = jax.device_get((old, new))
    if o.up.shape != n.up.shape or o.down.shape != n.down.shape:
        raise ValueError(
            f"view geometries differ: up {o.up.shape} vs {n.up.shape}, "
            f"down {o.down.shape} vs {n.down.shape}"
        )
    return ViewDelta(
        epoch_from=int(o.epoch), epoch_to=int(n.epoch),
        step_from=int(o.step), step_to=int(n.step),
        tape_cursor_from=int(o.tape_cursor),
        tape_cursor_to=int(n.tape_cursor),
        n_up_changed=int(
            np.sum(np.asarray(o.pool.osd_up) != np.asarray(n.pool.osd_up))
        ),
        n_down_changed=int(
            np.sum(np.asarray(o.down) != np.asarray(n.down))
        ),
        n_out_changed=int(np.sum(np.asarray(o.out) != np.asarray(n.out))),
        n_pgs_remapped=int(np.sum(
            np.any(np.asarray(o.acting) != np.asarray(n.acting), axis=-1)
        )),
    )


# ---------------------------------------------------------------------------
# compiled O(delta) incremental application


def _pad_to(n: int) -> int:
    """Pad bucket for a delta of ``n`` rows: next power of two (min 1),
    so delta *size* never changes the compiled program's shape."""
    p = 1
    while p < n:
        p <<= 1
    return p


def incremental_arrays(
    inc: Incremental,
    n_osds: int,
    pads: tuple[int, int, int] | None = None,
):
    """Compile one Incremental's per-OSD edits into fixed-shape scatter
    rows: ``(s_idx, s_up, s_ex, w_idx, w_val, a_idx, a_val)``, each
    padded to a power of two with out-of-range indices (``n_osds``)
    that the device scatter drops.

    ``pads`` pins the ``(state, weight, affinity)`` pad widths instead
    of deriving them per-delta — the fleet path uses this to give every
    cluster's delta the same shape so one vmapped scatter covers all.

    Raises for structural edits (:data:`_STRUCTURAL_FIELDS`,
    ``new_max_osd``): those change shapes or rewrite padded dict
    tables and take the :meth:`ClusterState.from_osdmap` rebuild.
    """
    if inc.new_max_osd is not None:
        raise ValueError(
            "new_max_osd resizes every per-OSD lane; rebuild via "
            "ClusterState.from_osdmap"
        )
    for f in _STRUCTURAL_FIELDS:
        if getattr(inc, f):
            raise ValueError(
                f"incremental field {f!r} is structural (dict-table "
                "rewrite); rebuild via ClusterState.from_osdmap"
            )
    from ..osdmap.map import EXISTS, UP

    forced = iter(pads) if pads is not None else None

    def rows(items, conv):
        idx = sorted(int(o) for o in items)
        pad = _pad_to(len(idx)) if forced is None else next(forced)
        if len(idx) > pad:
            raise ValueError(
                f"delta of {len(idx)} rows exceeds forced pad {pad}"
            )
        out_idx = np.full(pad, n_osds, np.int32)  # OOB pad -> dropped
        out_idx[: len(idx)] = idx
        vals = [conv(items[o]) for o in idx]
        return out_idx, vals, pad

    s_idx, s_vals, s_pad = rows(inc.new_state, int)
    s_up = np.zeros(s_pad, bool)
    s_ex = np.zeros(s_pad, bool)
    for j, v in enumerate(s_vals):
        s_up[j] = bool(v & UP)
        s_ex[j] = bool(v & EXISTS)
    w_idx, w_vals, w_pad = rows(inc.new_weight, int)
    w_val = np.zeros(w_pad, np.uint32)
    w_val[: len(w_vals)] = w_vals
    a_idx, a_vals, a_pad = rows(inc.new_primary_affinity, int)
    a_val = np.zeros(a_pad, np.uint32)
    a_val[: len(a_vals)] = a_vals
    return (
        jnp.asarray(s_idx), jnp.asarray(s_up), jnp.asarray(s_ex),
        jnp.asarray(w_idx), jnp.asarray(w_val),
        jnp.asarray(a_idx), jnp.asarray(a_val),
    )


@functools.lru_cache(maxsize=None)
def _apply_delta_fn(s_pad: int, w_pad: int, a_pad: int):
    """One compiled scatter program per (pad-bucket triple) — deltas of
    any size within the buckets reuse it."""

    @jax.jit
    def apply(state: ClusterState, epoch,
              s_idx, s_up, s_ex, w_idx, w_val, a_idx, a_val):
        pool = state.pool
        n = pool.osd_up.shape[0]
        cid = jnp.clip(s_idx, 0, n - 1)
        # the reference xors raw state bits; the resident lanes store
        # the *effective* bits (osd_up = exists & up), so: an UP xor
        # flips the stored up bit only while the OSD exists (the raw
        # bit on a non-existing OSD is invisible — build_incremental
        # never emits that row), and an EXISTS flip to False forces
        # the effective up bit False.
        new_ex = pool.osd_exists[cid] ^ s_ex
        new_up = (pool.osd_up[cid] ^ (s_up & pool.osd_exists[cid])) & new_ex
        osd_up = pool.osd_up.at[s_idx].set(new_up, mode="drop")
        osd_exists = pool.osd_exists.at[s_idx].set(new_ex, mode="drop")
        osd_weight = pool.osd_weight.at[w_idx].set(w_val, mode="drop")
        affinity = pool.primary_affinity.at[a_idx].set(a_val, mode="drop")
        return replace(
            state,
            pool=replace(
                pool,
                osd_up=osd_up,
                osd_exists=osd_exists,
                osd_weight=osd_weight,
                primary_affinity=affinity,
            ),
            epoch=epoch,
        )

    return apply


def apply_incremental(state: ClusterState, inc: Incremental) -> ClusterState:
    """Apply one epoch delta to the resident state as a compiled
    O(delta) scatter — the device twin of
    :meth:`ceph_tpu.osdmap.map.OSDMap.apply_incremental` for the
    per-OSD hot-loop fields.  The new map epoch comes from the
    incremental itself (no device scalar is pulled to host); callers
    that interleave host-map and device-state application keep them in
    lockstep by construction, and the differential tests assert it."""
    n = state.n_osds
    arrs = incremental_arrays(inc, n)
    fn = _apply_delta_fn(
        int(arrs[0].shape[0]), int(arrs[3].shape[0]), int(arrs[5].shape[0])
    )
    return fn(state, jnp.int32(inc.epoch), *arrs)


# ---------------------------------------------------------------------------
# fleets: a leading cluster batch axis over the same pytree


def stack_states(states) -> ClusterState:
    """Stack N independent :class:`ClusterState` pytrees into one fleet
    pytree: every leaf gains a leading ``[fleet, ...]`` axis and the
    result is still a ``ClusterState``, so the vmapped fleet superstep
    (:mod:`ceph_tpu.recovery.fleet`) can carry it through ``lax.scan``
    unchanged.  All members must share geometry (same leaf shapes) and
    agree on checksum presence — a mixed fleet has no single pytree
    structure."""
    states = list(states)
    if not states:
        raise ValueError("stack_states needs at least one state")
    with_ck = sum(1 for s in states if s.checksums is not None)
    if with_ck not in (0, len(states)):
        raise ValueError(
            "checksum tables must be attached to every fleet member "
            f"or none ({with_ck}/{len(states)} have one)"
        )
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)


def index_state(fleet: ClusterState, i: int) -> ClusterState:
    """Slice cluster ``i`` back out of a :func:`stack_states` fleet."""
    return jax.tree_util.tree_map(lambda x: x[i], fleet)


def fleet_incremental_arrays(incs, n_osds: int):
    """Batch per-cluster Incrementals into stacked scatter rows.

    All clusters share one ``(state, weight, affinity)`` pad triple —
    the power-of-two bucket of the *largest* delta per lane — so the
    vmapped scatter's shape depends only on the buckets, never on which
    cluster had the biggest delta.  Returns ``(epochs, arrays, pads)``
    where each array is ``[fleet, pad]``.
    """
    incs = list(incs)
    if not incs:
        raise ValueError("fleet_incremental_arrays needs >= 1 delta")
    pads = (
        _pad_to(max(len(i.new_state) for i in incs)),
        _pad_to(max(len(i.new_weight) for i in incs)),
        _pad_to(max(len(i.new_primary_affinity) for i in incs)),
    )
    from ..analysis import runtime_guard

    if runtime_guard.bucket_checks_enabled():
        runtime_guard.assert_bucketed(
            "cluster_state.fleet_incremental_arrays pads", *pads
        )
    per = [incremental_arrays(i, n_osds, pads=pads) for i in incs]
    arrays = tuple(jnp.stack(col) for col in zip(*per))
    epochs = jnp.asarray([int(i.epoch) for i in incs], I32)
    return epochs, arrays, pads


@functools.lru_cache(maxsize=None)
def _apply_fleet_delta_fn(s_pad: int, w_pad: int, a_pad: int):
    """The vmapped twin of :func:`_apply_delta_fn`: one compiled
    program per pad-bucket triple, batched over the fleet axis."""
    return jax.jit(jax.vmap(_apply_delta_fn(s_pad, w_pad, a_pad)))


def apply_incremental_fleet(fleet: ClusterState, incs) -> ClusterState:
    """Apply one per-cluster epoch delta to every fleet member as a
    single compiled vmapped scatter — batched O(delta) application.
    ``incs`` must have exactly one Incremental per fleet member (pad
    clusters take an empty ``Incremental(epoch=...)`` no-op)."""
    incs = list(incs)
    fleet_n = int(fleet.epoch.shape[0])
    if len(incs) != fleet_n:
        raise ValueError(
            f"{len(incs)} incrementals for a fleet of {fleet_n}"
        )
    n_osds = int(fleet.pool.osd_weight.shape[-1])
    epochs, arrays, pads = fleet_incremental_arrays(incs, n_osds)
    return _apply_fleet_delta_fn(*pads)(fleet, epochs, *arrays)


# ---------------------------------------------------------------------------
# dirty-set compaction: gather -> compute-on-bucket -> scatter
#
# The dense epoch engines peer/classify every PG (and every fleet lane)
# each dirty epoch even when a single OSD flap touched a handful of
# PGs.  The compacted path packs the dirty indices to the front of a
# fixed-width power-of-two bucket, runs the per-row kernels on the
# bucket only, and scatters results back with drop-mode OOB sentinels —
# the same bucketing discipline as the incremental-delta scatters
# above, so dirty-set *size* never changes a jit signature (J013 clean
# by construction).  Bucket widths form a small static ladder; a
# ``lax.switch`` on the traced dirty count picks the narrowest rung
# that fits, with the dense full-width path as the top rung (the
# bit-equality reference and the graceful-degradation fallback).


def compact_dirty_indices(dirty):
    """Stable-compact a boolean dirty mask into front-packed indices.

    Returns ``(take, n_dirty)`` where ``take`` is a length-``n`` i32
    vector whose first ``n_dirty`` entries are the dirty row indices in
    ascending order and whose remaining entries are the out-of-range
    sentinel ``n`` — so ``take[:W]`` feeds a clamped gather and a
    drop-mode scatter without any extra masking for the pad slots.
    Pure device arithmetic (one cumsum + one scatter); safe under jit
    and ``lax.scan``."""
    n = dirty.shape[0]
    flag = dirty.astype(I32)
    pos = jnp.cumsum(flag) - 1
    take = jnp.full((n,), n, I32).at[
        jnp.where(dirty, pos, n)
    ].set(jnp.arange(n, dtype=I32), mode="drop")
    return take, jnp.sum(flag)


def dirty_ladder(
    total: int, *, min_bucket: int = 32, growth: int = 4,
    max_rungs: int = 4,
) -> tuple[int, ...]:
    """Static compacted bucket widths strictly below ``total``.

    Each rung is the power-of-two bucket (:func:`_pad_to`) of the
    previous rung scaled by ``growth``, starting from ``min_bucket``,
    capped at ``max_rungs`` entries.  The dense full width is NOT
    included — callers append their existing dense branch as the top
    rung.  An empty tuple means the geometry is too small for
    compaction to have any rung below dense (callers fall back to the
    dense path).  Host-side ints only; widths are asserted
    power-of-two under ``debug_bucket_checks``."""
    widths: list[int] = []
    w = _pad_to(max(1, int(min_bucket)))
    while w < int(total) and len(widths) < int(max_rungs):
        widths.append(w)
        w = _pad_to(w * max(2, int(growth)))
    from ..analysis import runtime_guard

    if widths and runtime_guard.bucket_checks_enabled():
        runtime_guard.assert_bucketed(
            "cluster_state.dirty_ladder widths", *widths
        )
    return tuple(widths)


def ladder_rung(n_dirty, widths: tuple[int, ...]):
    """Traced ladder index for a traced dirty count: the narrowest
    rung in ``widths`` that holds ``n_dirty`` rows, or ``len(widths)``
    (the caller's dense branch) when none does.  The comparison runs on
    device so the selection never forces a host transfer inside the
    scanned epoch body."""
    if not widths:
        return jnp.int32(0)
    return jnp.sum(n_dirty > jnp.asarray(widths, I32)).astype(I32)


def gather_rows(table, take, width: int):
    """Gather the first ``width`` compacted rows of ``table``.

    ``width`` must be a static ladder rung (power of two from
    :func:`dirty_ladder`); pad slots carry the sentinel index and clamp
    to row ``n - 1`` — garbage rows that the matching
    :func:`scatter_rows` drops on the way back."""
    n = table.shape[0]
    idx = jnp.clip(take[:width], 0, n - 1)
    return table[idx]


def scatter_rows(table, take, width: int, vals):
    """Scatter ``width`` computed rows back to their dirty slots.

    Pad slots of ``take`` hold the out-of-range sentinel ``n`` and are
    dropped by the scatter, so clean rows keep their carried values
    bit-for-bit; the dirty indices are unique by construction (one
    cumsum slot each) so there are no duplicate-write races."""
    return table.at[take[:width]].set(vals, mode="drop")


def bucket_valid(n_dirty, width: int):
    """Boolean validity mask for a compacted bucket: lane ``j`` holds a
    real dirty row iff ``j < n_dirty``.  Needed only by reductions that
    fold bucket lanes into scalars (e.g. pg_hist deltas) — plain
    gather/scatter round-trips are already pad-safe via the sentinel."""
    return jnp.arange(width, dtype=I32) < n_dirty
