"""Fused Pallas TPU kernel for the straw2 negdraw (the CRUSH hot op).

Computes, per lane, the exact :func:`ceph_tpu.core.hashes.straw2_negdraw_magic`
pipeline — rjenkins hash -> ``crush_ln`` LUT walk -> magic-reciprocal
division (upstream ``src/crush/mapper.c :: bucket_straw2_choose`` +
``crush_ln`` + ``src/crush/hash.c``) — entirely inside VMEM.

Why (round-3 silicon profiling): the XLA path spends ~300 ms per
[1M, 8] straw2 call in ``crush_ln``'s three per-lane LUT gathers; the
chip lowers any HBM-level gather at ~10 ns/lane regardless of table
size, while every other part of straw2 costs ~4 ms.  The fix is the
TPU's native in-register table unit: ``tpu.dynamic_gather`` handles a
128-wide lane-resident LUT in one op, but only via Pallas (XLA never
emits it for these shapes).

Kernel facts:

- All arithmetic is u32; the u64 quantities (crush_ln's 48-bit fixed
  point, the 64-bit magic reciprocal, the 128-bit mulhi) are carried
  as 16-bit limbs with explicit carries — Mosaic has no 64-bit ints.
- The 256/129-entry LUTs are split into 128-entry lane-resident
  halves and read with ``jnp.take_along_axis(..., axis=1)`` (lowers
  to one ``tpu.dynamic_gather`` each); the single boundary entry
  (``xs == 0x10000``) is a constant select.
- ``31 - clz(x)`` is a sum of 16 compares (no clz in Mosaic).
- Traced with x64 scoped off (i64 in index maps breaks Mosaic; see
  pallas_kernels.py).

Bit-exactness is enforced by tests/test_pallas_straw2.py (interpret
mode vs the jnp path over random draws incl. boundary cases) and on
silicon by the TPU tier.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from .. import enable_x64 as _enable_x64

from . import hashes

U32 = jnp.uint32
I32 = jnp.int32

SUBLANES = 256          # tile = [SUBLANES, 128]
TILE = SUBLANES * 128   # elements per grid step

_M16 = np.uint32(0xFFFF)
_U32MAX = np.uint32(0xFFFFFFFF)


def _build_tables() -> tuple[np.ndarray, np.uint32, np.uint32, np.uint32, np.uint32]:
    """Pack the crush_ln LUTs into one [8, 128] u32 array.

    Rows: rh_lo, rh_hi, lh_lo, lh_hi, ll0_lo, ll0_hi, ll1_lo, ll1_hi
    (lo/hi = 32-bit halves of the <2^48 u64 entries; rh/lh indexed by
    ``k = (xs >> 8) - 128`` in [0, 128); ll0/ll1 = LL table halves for
    index2 < 128 / >= 128).  Returns the boundary entries (k == 128,
    i.e. xs == 0x10000) separately as scalars.
    """
    rh_lh = hashes._RH_LH_NP
    ll = hashes._LL_NP
    assert rh_lh.shape[0] >= 258 and ll.shape[0] >= 256
    rh = rh_lh[0:256:2]      # k = 0..127
    lh = rh_lh[1:256:2]
    t = np.zeros((8, 128), np.uint32)
    t[0] = (rh & 0xFFFFFFFF).astype(np.uint32)
    t[1] = (rh >> np.uint64(32)).astype(np.uint32)
    t[2] = (lh & 0xFFFFFFFF).astype(np.uint32)
    t[3] = (lh >> np.uint64(32)).astype(np.uint32)
    t[4] = (ll[:128] & 0xFFFFFFFF).astype(np.uint32)
    t[5] = (ll[:128] >> np.uint64(32)).astype(np.uint32)
    t[6] = (ll[128:256] & 0xFFFFFFFF).astype(np.uint32)
    t[7] = (ll[128:256] >> np.uint64(32)).astype(np.uint32)
    rb, lb = rh_lh[256], rh_lh[257]
    return (
        t,
        np.uint32(rb & 0xFFFFFFFF), np.uint32(rb >> np.uint64(32)),
        np.uint32(lb & 0xFFFFFFFF), np.uint32(lb >> np.uint64(32)),
    )


_TBL, _RH_B_LO, _RH_B_HI, _LH_B_LO, _LH_B_HI = _build_tables()


def _lut(tbl, row: int, idx):
    """128-entry lane-resident lookup: T[row][idx] via dynamic_gather."""
    t = jnp.broadcast_to(tbl[row:row + 1, :], idx.shape)
    return jnp.take_along_axis(t, idx, axis=1)


def _mulhi_3x4(a0, a1, a2, m0, m1, m2, m3):
    """bits 64..111 of (a2:a1:a0 16-bit limbs) * (m3:m2:m1:m0), as two
    u32 digits (lo32, hi16).  a2 may be up to 0x10000 (17 bits): every
    partial product still fits u32 (0x10000 * 0xFFFF < 2^32)."""
    ps = {}
    for i, av in enumerate((a0, a1, a2)):
        for j, mv in enumerate((m0, m1, m2, m3)):
            ps[i, j] = av * mv
    # column digit sums, split into lo/hi 16 first so no sum overflows
    g = [None] * 7  # g[k] multiplies 2^(16k); g6 collects col5's hi
    for k in range(6):
        lo = jnp.zeros_like(a0)
        hi = jnp.zeros_like(a0)
        for i in range(3):
            j = k - i
            if 0 <= j < 4:
                lo = lo + (ps[i, j] & _M16)
                hi = hi + (ps[i, j] >> 16)
        g[k] = lo if g[k] is None else g[k] + lo
        nxt = g[k + 1] if k + 1 < 7 and g[k + 1] is not None else None
        g[k + 1] = hi if nxt is None else nxt + hi
    carry = jnp.zeros_like(a0)
    digits = []
    for k in range(7):
        t = g[k] + carry
        digits.append(t & _M16)
        carry = t >> 16
    q_lo = digits[4] | (digits[5] << 16)
    q_hi = digits[6] | (carry << 16)
    return q_lo, q_hi


def _mullo_3x2(q0, q1, q2, q3, w0, w1):
    """low 64 bits of (q3:q2:q1:q0) * (w1:w0) as (lo32, hi32).

    q3 (bits 48..63 of q) matters exactly when the quotient is 2^48 —
    reachable at u==0 with weight 1 — where dropping it wrapped the
    correction product and broke bit-exactness (round-3 advisor).  Only
    q3*w0's low 16 bits can land in digit 3; higher partials overflow
    bit 63 and are discarded.
    """
    p00 = q0 * w0
    p01 = q0 * w1
    p10 = q1 * w0
    p11 = q1 * w1
    p20 = q2 * w0
    p21 = q2 * w1
    p30 = q3 * w0
    g0 = p00 & _M16
    g1 = (p00 >> 16) + (p01 & _M16) + (p10 & _M16)
    g2 = (p01 >> 16) + (p10 >> 16) + (p11 & _M16) + (p20 & _M16)
    g3 = (p11 >> 16) + (p20 >> 16) + (p21 & _M16) + (p30 & _M16)
    c = g0 >> 16
    d0 = g0 & _M16
    t = g1 + c
    d1 = t & _M16
    c = t >> 16
    t = g2 + c
    d2 = t & _M16
    c = t >> 16
    d3 = (g3 + c) & _M16
    return d0 | (d1 << 16), d2 | (d3 << 16)


def _straw2_math(x, item, r, w, mlo, mhi, tbl):
    """Per-lane straw2 negdraw as u32 ops (the kernel body; shapes all
    [S, 128]).  Returns (nd_lo, nd_hi) with w == 0 -> U64MAX."""
    # ---- rjenkins hash (hashes.crush_hash32_3, inlined u32 ops) ----
    a, b, c = x, item, r
    h = hashes.CRUSH_HASH_SEED ^ a ^ b ^ c
    hx = jnp.full_like(a, 231232)
    hy = jnp.full_like(a, 1232)
    a, b, h = hashes.hashmix(a, b, h)
    c, hx, h = hashes.hashmix(c, hx, h)
    hy, a, h = hashes.hashmix(hy, a, h)
    b, hx, h = hashes.hashmix(b, hx, h)
    hy, c, h = hashes.hashmix(hy, c, h)
    u = h & _M16

    # ---- crush_ln (hashes.crush_ln, LUTs via dynamic_gather) ----
    xv = u + np.uint32(1)                      # [1, 0x10000]
    p = jnp.zeros_like(xv)
    for k in range(1, 17):                     # p = 31 - clz(xv)
        p = p + (xv >= np.uint32(1 << k)).astype(U32)
    need = p < np.uint32(15)
    shift = jnp.where(need, np.uint32(15) - p, np.uint32(0))
    xs = xv << shift                           # [0x8000, 0x10000]
    iexpon = jnp.where(need, p, np.uint32(15))
    kidx = (xs >> 8) - np.uint32(128)          # [0, 128]
    bound = kidx == np.uint32(128)
    # minui doesn't legalize in Mosaic; kidx <= 128 so signed min is safe
    li = jnp.minimum(kidx.astype(I32), np.int32(127))
    rh_lo = jnp.where(bound, _RH_B_LO, _lut(tbl, 0, li))
    rh_hi = jnp.where(bound, _RH_B_HI, _lut(tbl, 1, li))
    lh_lo = jnp.where(bound, _LH_B_LO, _lut(tbl, 2, li))
    lh_hi = jnp.where(bound, _LH_B_HI, _lut(tbl, 3, li))

    # index2 = ((xs * rh) >> 48) & 0xff ; xs <= 2^16, rh < 2^48
    pa = xs * (rh_lo & _M16)
    pb = xs * (rh_lo >> 16)
    pc = xs * rh_hi                            # rh_hi < 2^16
    s = (pa >> 16) + pb
    hi32t = pc + (s >> 16)
    idx2 = (hi32t >> 16) & np.uint32(0xFF)
    half = idx2 >= np.uint32(128)
    l2 = (idx2 & np.uint32(127)).astype(I32)
    ll_lo = jnp.where(half, _lut(tbl, 6, l2), _lut(tbl, 4, l2))
    ll_hi = jnp.where(half, _lut(tbl, 7, l2), _lut(tbl, 5, l2))

    # ln = (iexpon << 44) + ((lh + ll) >> 4)   (< 2^48, as hi16:lo32)
    sum_lo = lh_lo + ll_lo
    carry = (sum_lo < lh_lo).astype(U32)
    sum_hi = lh_hi + ll_hi + carry
    ln_lo = (sum_lo >> 4) | (sum_hi << 28)
    ln_hi = (sum_hi >> 4) + (iexpon << 12)

    # ln_neg = 2^48 - ln
    neg_lo = np.uint32(0) - ln_lo
    borrow = (ln_lo != np.uint32(0)).astype(U32)
    neg_hi = np.uint32(0x10000) - ln_hi - borrow

    # ---- q = floor(ln_neg / w) via magic (hashes.div_by_magic) ----
    a0 = neg_lo & _M16
    a1 = neg_lo >> 16
    a2 = neg_hi                                # <= 0x10000
    m0 = mlo & _M16
    m1 = mlo >> 16
    m2 = mhi & _M16
    m3 = mhi >> 16
    q_lo, q_hi = _mulhi_3x4(a0, a1, a2, m0, m1, m2, m3)

    wsafe = jnp.where(w == np.uint32(0), np.uint32(1), w)  # maxui: no Mosaic
    w0 = wsafe & _M16
    w1 = wsafe >> 16
    for _ in range(3):                         # same 3 corrections
        qw_lo, qw_hi = _mullo_3x2(q_lo & _M16, q_lo >> 16, q_hi & _M16,
                                  q_hi >> 16, w0, w1)
        rem_lo = neg_lo - qw_lo
        rb = (neg_lo < qw_lo).astype(U32)
        rem_hi = neg_hi - qw_hi - rb
        over = (rem_hi != np.uint32(0)) | (rem_lo >= wsafe)
        inc = over.astype(U32)
        nq_lo = q_lo + inc
        q_hi = q_hi + ((nq_lo == 0) & over).astype(U32)
        q_lo = nq_lo

    zero = w == np.uint32(0)
    return jnp.where(zero, _U32MAX, q_lo), jnp.where(zero, _U32MAX, q_hi)


def _kernel(x_ref, id_ref, r_ref, w_ref, mlo_ref, mhi_ref, tbl_ref,
            lo_ref, hi_ref):
    lo, hi = _straw2_math(
        x_ref[:, :], id_ref[:, :], r_ref[:, :], w_ref[:, :],
        mlo_ref[:, :], mhi_ref[:, :], tbl_ref[:, :],
    )
    lo_ref[:, :] = lo
    hi_ref[:, :] = hi


def _negdraw_call(xf, idf, rf, wf, mlo, mhi, interpret: bool):
    with _enable_x64(False):
        return _negdraw_jit(xf, idf, rf, wf, mlo, mhi, interpret)


@partial(jax.jit, static_argnums=(6,))
def _negdraw_jit(xf, idf, rf, wf, mlo, mhi, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = xf.shape[0]
    rows = n // 128
    grid = (rows // SUBLANES,)
    bs = lambda: pl.BlockSpec((SUBLANES, 128), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    args = [v.reshape(rows, 128) for v in (xf, idf, rf, wf, mlo, mhi)]
    out = pl.pallas_call(
        _kernel,
        out_shape=(jax.ShapeDtypeStruct((rows, 128), jnp.uint32),
                   jax.ShapeDtypeStruct((rows, 128), jnp.uint32)),
        grid=grid,
        in_specs=[bs() for _ in range(6)] + [
            pl.BlockSpec((8, 128), lambda i: (0, 0),
                         memory_space=pltpu.VMEM)],
        out_specs=(bs(), bs()),
        interpret=interpret,
    )(*args, jnp.asarray(_TBL))
    return out[0].reshape(n), out[1].reshape(n)


# ---------------------------------------------------------------------------
# Level-descent kernel: one whole straw2 choose (row fetch + F-way draw +
# first-wins argmin + winner field select) per call.  Removes the XLA-side
# one-hot row matmul, the [B, F] HBM intermediates and the u64 argmin —
# per-level HBM traffic drops to ~7 words/lane.
# ---------------------------------------------------------------------------

MAX_HALVES = 4   # level tables up to 4*128 buckets ride the kernel
MAX_FANOUT = 32  # per-child straw2 unroll bound (compile time/VMEM)


def _gather_halves(row_fn, halves: int, lidx, li):
    """Per-lane bucket-table read from 128-lane halves: ``row_fn(h)``
    returns the [1, 128] lane vector for half ``h``; ``li`` is
    ``lidx & 127`` and lanes pick their half by ``lidx >> 7``."""
    v = jnp.take_along_axis(jnp.broadcast_to(row_fn(0), li.shape), li, axis=1)
    for h in range(1, halves):
        vh = jnp.take_along_axis(
            jnp.broadcast_to(row_fn(h), li.shape), li, axis=1)
        v = jnp.where((lidx >> 7) == np.uint32(h), vh, v)
    return v


def _bucket_field(tbl_ref, field: int, f: int, halves: int, lidx, li):
    """tbl[field, f, lidx] for a [NF, F, H, 128] level table."""
    return _gather_halves(
        lambda h: tbl_ref[field, f, h:h + 1, :], halves, lidx, li)


def _make_level_kernel(fanout: int, halves: int):
    def kern(x_ref, r_ref, lidx_ref, tbl_ref, lut_ref,
             item_ref, ctnl_ref, size_ref):
        x = x_ref[:, :]
        r = r_ref[:, :]
        lidx = lidx_ref[:, :]
        lut = lut_ref[:, :]
        li = (lidx & np.uint32(127)).astype(I32)

        # bucket size (per lidx, field 5 holds it at f=0)
        size = _bucket_field(tbl_ref, 5, 0, halves, lidx, li)

        best_lo = best_hi = None
        chosen = ctnl = None
        for f in range(fanout):
            idf = _bucket_field(tbl_ref, 0, f, halves, lidx, li)
            wf = _bucket_field(tbl_ref, 1, f, halves, lidx, li)
            mlo = _bucket_field(tbl_ref, 2, f, halves, lidx, li)
            mhi = _bucket_field(tbl_ref, 3, f, halves, lidx, li)
            ctnlf = _bucket_field(tbl_ref, 4, f, halves, lidx, li)
            nd_lo, nd_hi = _straw2_math(x, idf, r, wf, mlo, mhi, lut)
            if f == 0:
                best_lo, best_hi = nd_lo, nd_hi
                chosen, ctnl = idf, ctnlf
            else:
                # strict less-than keeps first-index tie semantics
                upd = (nd_hi < best_hi) | (
                    (nd_hi == best_hi) & (nd_lo < best_lo))
                best_lo = jnp.where(upd, nd_lo, best_lo)
                best_hi = jnp.where(upd, nd_hi, best_hi)
                chosen = jnp.where(upd, idf, chosen)
                ctnl = jnp.where(upd, ctnlf, ctnl)

        item_ref[:, :] = chosen
        ctnl_ref[:, :] = ctnl
        size_ref[:, :] = size
    return kern


def _level_sublanes(fanout: int) -> int:
    """Tile height for the level kernel: the F-unrolled straw2 keeps
    ~10 live [sub, 128] u32 temporaries per child, and the whole
    working set must fit the chip's 16 MB scoped VMEM (F=16 at
    sub=256 OOMs at 19.5 MB — found by local chipless AOT compile).
    Budget ~6 MB: sub = 1536/F clamped to [8, 256], multiple of 8."""
    sub = max(8, min(SUBLANES, (1536 // max(fanout, 1)) // 8 * 8))
    return sub


def _level_call(xf, rf, lidxf, tbl, interpret: bool):
    with _enable_x64(False):
        return _level_jit(xf, rf, lidxf, tbl, interpret)


@partial(jax.jit, static_argnums=(4,))
def _level_jit(xf, rf, lidxf, tbl, interpret):
    """Inputs are FLAT [N] u32 arrays, N a multiple of
    ``_level_sublanes(fanout) * 128``."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nf, fanout, halves, _ = tbl.shape
    n = xf.shape[0]
    rows = n // 128
    sub = _level_sublanes(fanout)
    grid = (rows // sub,)
    bs = lambda: pl.BlockSpec((sub, 128), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _make_level_kernel(fanout, halves),
        out_shape=(jax.ShapeDtypeStruct((rows, 128), jnp.uint32),) * 3,
        grid=grid,
        in_specs=[bs(), bs(), bs(),
                  pl.BlockSpec((nf, fanout, halves, 128),
                               lambda i: (0, 0, 0, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((8, 128), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=(bs(), bs(), bs()),
        interpret=interpret,
    )(xf.reshape(rows, 128), rf.reshape(rows, 128),
      lidxf.reshape(rows, 128), tbl, jnp.asarray(_TBL))
    return out


def pack_level_table(ids: np.ndarray, weights: np.ndarray,
                     magic: np.ndarray, ctype: np.ndarray,
                     nlidx: np.ndarray, sizes: np.ndarray) -> np.ndarray | None:
    """Host-side pack of one BFS level into the kernel's [6, F, H, 128]
    u32 layout (fields: id, w, magic_lo, magic_hi, ctype<<16|nlidx,
    size).  Returns None when the level exceeds the kernel's bounds."""
    nb, fanout = ids.shape
    halves = (max(nb, 1) + 127) // 128
    if halves > MAX_HALVES or not 1 <= fanout <= MAX_FANOUT:
        # wide flat buckets would unroll one full _straw2_math per
        # child into a single Mosaic kernel (compile-time/VMEM blowup);
        # the XLA [B, F] path handles any fanout
        return None
    if nlidx.max(initial=0) > 0xFFFF or ctype.max(initial=0) > 0xFF:
        return None
    t = np.zeros((6, fanout, halves, 128), np.uint32)
    pad = halves * 128
    for f in range(fanout):
        for field, arr in ((0, ids[:, f]), (1, weights[:, f]),
                           (2, (magic[:, f] & 0xFFFFFFFF).astype(np.uint32)),
                           (3, (magic[:, f] >> np.uint64(32)).astype(np.uint32)),
                           (4, (ctype[:, f].astype(np.uint32) << 16)
                               | nlidx[:, f].astype(np.uint32))):
            a = np.zeros((pad,), np.uint32)
            a[:nb] = arr.astype(np.uint32)
            t[field, f] = a.reshape(halves, 128)
    a = np.zeros((pad,), np.uint32)
    a[:nb] = sizes.astype(np.uint32)
    t[5, :] = np.broadcast_to(a.reshape(halves, 128), (fanout, halves, 128))
    return t


def level_choose(x, r, lidx, tbl, interpret: bool | None = None):
    """One straw2 level choose for a [B] batch.

    Returns (item u32, ctype i32, nlidx i32, size i32), all [B].
    ``tbl`` is the pack_level_table output as a device array."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = x.shape[0]
    gran = _level_sublanes(int(tbl.shape[1])) * 128
    npad = (n + gran - 1) // gran * gran
    u32 = lambda v: jnp.asarray(v).astype(U32)
    xf, rf, lf = u32(x), u32(r), u32(lidx)
    if npad != n:
        pad = lambda v: jnp.pad(v, (0, npad - n))
        xf, rf, lf = pad(xf), pad(rf), pad(lf)
    item, ctnl, size = _level_call(xf, rf, lf, tbl, interpret)
    item = item.reshape(-1)[:n]
    ctnl = ctnl.reshape(-1)[:n]
    size = size.reshape(-1)[:n]
    return (item, (ctnl >> 16).astype(jnp.int32),
            (ctnl & jnp.uint32(0xFFFF)).astype(jnp.int32),
            size.astype(jnp.int32))


# ---------------------------------------------------------------------------
# Whole-descent kernel: ALL hierarchy levels of one descent in a single
# Pallas call.  The per-level kernel already removed the HBM row fetch;
# this removes the per-level kernel-call boundary too, so an engine
# program embeds one Mosaic kernel per descend site instead of one per
# (site x level) — the compile-time blowup that kept the kernel path
# opt-in (round 3).
# ---------------------------------------------------------------------------

ITEM_NONE_U32 = np.uint32(0x7FFFFFFF)
_CT_DANGLING = np.uint32(255)


MAX_DESC_TABLE_BYTES = 4 << 20  # stacked-table VMEM budget


def pack_descend_tables(levels_packed) -> tuple[np.ndarray, tuple] | None:
    """Stack per-level lane tables (pack_level_table outputs) into one
    [L, 6, Fmax, Hmax, 128] u32 array.  Returns (stacked, meta) with
    meta = ((F0, H0), ...), or None if any level failed the per-level
    bounds or the padded stack would exceed the kernel's VMEM budget
    (the whole table is resident; per-level bounds alone don't cap a
    deep hierarchy)."""
    if any(t is None for t in levels_packed):
        return None
    meta = [(t.shape[1], t.shape[2]) for t in levels_packed]
    fmax = max(f for f, _ in meta)
    hmax = max(h for _, h in meta)
    nbytes = len(levels_packed) * 6 * fmax * hmax * 128 * 4
    if nbytes > MAX_DESC_TABLE_BYTES:
        return None
    out = np.zeros((len(levels_packed), 6, fmax, hmax, 128), np.uint32)
    for i, t in enumerate(levels_packed):
        out[i, :, : t.shape[1], : t.shape[2], :] = t
    return out, tuple(meta)


def _make_descend_kernel(meta: tuple, target_type: int,
                         empty_is_hard: bool, max_devices: int):
    def kern(x_ref, r_ref, lidx_ref, act_ref, tbl_ref, lut_ref,
             item_ref, aux_ref):
        x = x_ref[:, :]
        r = r_ref[:, :]
        lut = lut_ref[:, :]
        active = act_ref[:, :] != np.uint32(0)

        done = ~active
        ok = jnp.zeros_like(done)
        hard = jnp.zeros_like(done)
        item = jnp.full_like(x, ITEM_NONE_U32)
        nlidx_out = jnp.zeros_like(x)
        lidx = lidx_ref[:, :]

        for lv, (fanout, halves) in enumerate(meta):
            li = (lidx & np.uint32(127)).astype(I32)

            def bf(field, f):
                # f may be a traced i32 (fori_loop index): dynamic
                # indexing is on untiled leading dims only
                return _gather_halves(
                    lambda h: tbl_ref[lv, field, f, h:h + 1, :],
                    halves, lidx, li)

            size = bf(5, 0)

            def draw(f):
                idf = bf(0, f)
                ctnlf = bf(4, f)
                nd_lo, nd_hi = _straw2_math(
                    x, idf, r, bf(1, f), bf(2, f), bf(3, f), lut)
                return nd_lo, nd_hi, idf, ctnlf

            best_lo, best_hi, chosen, ctnl = draw(0)

            def fbody(f, st):
                # straw2 is traced ONCE per level (Mosaic compile time
                # is superlinear in kernel size; a fanout-unrolled body
                # took >17 min to compile at 3 levels x F=16)
                b_lo, b_hi, ch, ct = st
                nd_lo, nd_hi, idf, ctnlf = draw(f)
                upd = (nd_hi < b_hi) | ((nd_hi == b_hi) & (nd_lo < b_lo))
                return (jnp.where(upd, nd_lo, b_lo),
                        jnp.where(upd, nd_hi, b_hi),
                        jnp.where(upd, idf, ch),
                        jnp.where(upd, ctnlf, ct))

            if fanout > 1:
                # i32 bounds keep the counter i32 even when the caller
                # traces under x64 (enable_x64(False) cannot scope dtypes
                # once inside an outer jit trace)
                best_lo, best_hi, chosen, ctnl = jax.lax.fori_loop(
                    jnp.int32(1), jnp.int32(fanout), fbody,
                    (best_lo, best_hi, chosen, ctnl))

            ctype = ctnl >> 16
            nlidx = ctnl & np.uint32(0xFFFF)
            # mirror interp_batch.descend's per-level status block
            empty = size == np.uint32(0)
            is_bucket = chosen >= np.uint32(0x80000000)
            if target_type != 0:
                reached = ctype == np.uint32(target_type)
            else:
                reached = ~is_bucket
            wrong_dev = (~is_bucket) & (~reached)
            bad_dev = (~is_bucket) & (chosen >= np.uint32(max_devices))
            bad_bucket = is_bucket & (ctype == _CT_DANGLING)
            if empty_is_hard:
                hard_now = empty | wrong_dev | bad_dev | bad_bucket
                soft_now = jnp.zeros_like(empty)
            else:
                hard_now = (~empty) & (wrong_dev | bad_dev | bad_bucket)
                soft_now = empty
            new_done = done | hard_now | soft_now | reached
            ok = jnp.where(done, ok, reached & ~hard_now & ~soft_now)
            hard = jnp.where(done, hard, hard_now)
            item = jnp.where(done, item, chosen)
            nlidx_out = jnp.where(done, nlidx_out, nlidx)
            lidx = jnp.where(new_done, lidx, nlidx)
            done = new_done

        item_ref[:, :] = item
        aux_ref[:, :] = (nlidx_out
                         | (ok.astype(U32) << 16)
                         | (hard.astype(U32) << 17))
    return kern


def _descend_call(xf, rf, lidxf, actf, tbl, meta, target_type,
                  empty_is_hard, max_devices, interpret):
    with _enable_x64(False):
        return _descend_jit(xf, rf, lidxf, actf, tbl, meta, target_type,
                            empty_is_hard, max_devices, interpret)


@partial(jax.jit, static_argnums=(5, 6, 7, 8, 9))
def _descend_jit(xf, rf, lidxf, actf, tbl, meta, target_type,
                 empty_is_hard, max_devices, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = xf.shape[0]
    rows = n // 128
    fmax = max(f for f, _ in meta)
    sub = _level_sublanes(fmax)
    bs = lambda: pl.BlockSpec((sub, 128), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _make_descend_kernel(meta, target_type, empty_is_hard, max_devices),
        out_shape=(jax.ShapeDtypeStruct((rows, 128), jnp.uint32),) * 2,
        grid=(rows // sub,),
        in_specs=[bs(), bs(), bs(), bs(),
                  pl.BlockSpec(tbl.shape, lambda i: (0,) * 5,
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((8, 128), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=(bs(), bs()),
        interpret=interpret,
    )(xf.reshape(rows, 128), rf.reshape(rows, 128),
      lidxf.reshape(rows, 128), actf.reshape(rows, 128),
      tbl, jnp.asarray(_TBL))
    return out


def descend_fused(x, r, lidx, active, tbl, meta, target_type: int,
                  empty_is_hard: bool, max_devices: int,
                  interpret: bool | None = None):
    """Whole descent for a [B] batch in one kernel call.

    Returns (item i32, ok bool, hard bool, nlidx i32) — the contract of
    ``interp_batch.descend``'s level loop."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = x.shape[0]
    fmax = max(f for f, _ in meta)
    gran = _level_sublanes(fmax) * 128
    npad = (n + gran - 1) // gran * gran
    u32 = lambda v: jnp.asarray(v).astype(U32)
    xf, rf, lf = u32(x), u32(r), u32(lidx)
    af = jnp.asarray(active).astype(U32)
    if npad != n:
        pad = lambda v: jnp.pad(v, (0, npad - n))
        xf, rf, lf, af = pad(xf), pad(rf), pad(lf), pad(af)
    item_u, aux = _descend_call(xf, rf, lf, af, tbl, meta, target_type,
                                empty_is_hard, max_devices, interpret)
    item_u = item_u.reshape(-1)[:n]
    aux = aux.reshape(-1)[:n]
    import jax.lax as lax

    item = lax.bitcast_convert_type(item_u, jnp.int32)
    ok = (aux >> 16) & jnp.uint32(1)
    hard = (aux >> 17) & jnp.uint32(1)
    nlidx = (aux & jnp.uint32(0xFFFF)).astype(jnp.int32)
    return item, ok != 0, hard != 0, nlidx


def straw2_negdraw_fused(x, item_id, r, weight, magic,
                         interpret: bool | None = None):
    """Drop-in replacement for :func:`hashes.straw2_negdraw_magic`
    (same broadcastable [.., F] args, same u64 result), computed by the
    fused Pallas kernel.  Pads the flattened batch to the tile size;
    padding lanes compute garbage that is sliced off."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    shape = jnp.broadcast_shapes(
        jnp.shape(x), jnp.shape(item_id), jnp.shape(r),
        jnp.shape(weight), jnp.shape(magic))
    u32 = lambda v: jnp.broadcast_to(
        jnp.asarray(v).astype(U32), shape).reshape(-1)
    mg = jnp.broadcast_to(jnp.asarray(magic, jnp.uint64), shape).reshape(-1)
    xf, idf, rf, wf = u32(x), u32(item_id), u32(r), u32(weight)
    mlo = mg.astype(U32)
    mhi = (mg >> jnp.uint64(32)).astype(U32)
    n = xf.shape[0]
    npad = (n + TILE - 1) // TILE * TILE
    if npad != n:
        pad = lambda v: jnp.pad(v, (0, npad - n))
        xf, idf, rf, wf, mlo, mhi = map(pad, (xf, idf, rf, wf, mlo, mhi))
    lo, hi = _negdraw_call(xf, idf, rf, wf, mlo, mhi, interpret)
    nd = lo[:n].astype(jnp.uint64) | (hi[:n].astype(jnp.uint64) << jnp.uint64(32))
    return nd.reshape(shape)
