"""Vectorized (jnp) CRUSH core primitives.

Bit-exact counterparts of :mod:`ceph_tpu.core.ref`, written as
elementwise ops over ``uint32``/``uint64`` arrays so they can be
``vmap``-ed / fused by XLA.  Requires x64 mode (enabled at package
import): the straw2 draw needs a 64-bit unsigned divide, which XLA
emulates exactly on TPU via 32-bit pairs.

Design note (TPU-first): the signed ``div64_s64(ln, w)`` from the spec
(SURVEY.md §2.1, upstream ``src/crush/mapper.c :: bucket_straw2_choose``)
is rewritten as an UNSIGNED quantity ``negdraw = (2^48 - crush_ln(u)) // w``
-- ``ln <= 0`` and truncating signed division of a negative by a positive
equals the negated floor division of magnitudes, so ``argmax draw`` (ties:
first) becomes ``argmin negdraw`` (ties: first), with zero weight mapping
to ``UINT64_MAX``.  This keeps the hot loop in unsigned integer ops.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ._crush_ln_tables import LL_TBL, RH_LH_TBL

CRUSH_HASH_SEED = np.uint32(1315423911)
U64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)

# Host-side constants; jnp.asarray at use site embeds them as XLA
# constants (safe under tracing, deduped by the compiler).
_RH_LH_NP = np.array(RH_LH_TBL, dtype=np.uint64)
_LL_NP = np.array(LL_TBL, dtype=np.uint64)


def _tables():
    return jnp.asarray(_RH_LH_NP), jnp.asarray(_LL_NP)


def _u32(x):
    return jnp.asarray(x).astype(jnp.uint32)


def hashmix(a, b, c):
    """One rjenkins mix round; wrapping uint32 elementwise."""
    a = a - b - c
    a = a ^ (c >> 13)
    b = b - c - a
    b = b ^ (a << 8)
    c = c - a - b
    c = c ^ (b >> 13)
    a = a - b - c
    a = a ^ (c >> 12)
    b = b - c - a
    b = b ^ (a << 16)
    c = c - a - b
    c = c ^ (b >> 5)
    a = a - b - c
    a = a ^ (c >> 3)
    b = b - c - a
    b = b ^ (a << 10)
    c = c - a - b
    c = c ^ (b >> 15)
    return a, b, c


def crush_hash32_2(a, b):
    a = _u32(a)
    b = _u32(b)
    h = CRUSH_HASH_SEED ^ a ^ b
    x = jnp.full_like(a, 231232)
    y = jnp.full_like(a, 1232)
    a, b, h = hashmix(a, b, h)
    x, a, h = hashmix(x, a, h)
    b, y, h = hashmix(b, y, h)
    return h


def crush_hash32_3(a, b, c):
    a = _u32(a)
    b = _u32(b)
    c = _u32(c)
    h = CRUSH_HASH_SEED ^ a ^ b ^ c
    x = jnp.full_like(a, 231232)
    y = jnp.full_like(a, 1232)
    a, b, h = hashmix(a, b, h)
    c, x, h = hashmix(c, x, h)
    y, a, h = hashmix(y, a, h)
    b, x, h = hashmix(b, x, h)
    y, c, h = hashmix(y, c, h)
    return h


def ceph_stable_mod(x, b, bmask):
    """Vectorized stable_mod; all args broadcastable uint32/int32."""
    x = jnp.asarray(x)
    return jnp.where((x & bmask) < b, x & bmask, x & (bmask >> 1))


def crush_ln(u):
    """~2^44 * log2(u+1) for u in [0, 0xffff]; returns uint64."""
    rh_lh, ll_tbl = _tables()
    x = _u32(u) + np.uint32(1)  # [1, 0x10000]
    p = (np.int32(31) - lax.clz(x.astype(jnp.int32))).astype(jnp.uint32)
    need = p < 15
    shift = jnp.where(need, np.uint32(15) - p, np.uint32(0))
    xs = x << shift
    iexpon = jnp.where(need, p, np.uint32(15)).astype(jnp.uint64)
    index1 = ((xs >> 8) << 1).astype(jnp.int32)
    rh = rh_lh[index1 - 256]
    lh = rh_lh[index1 - 255]
    xl64 = (xs.astype(jnp.uint64) * rh) >> np.uint64(48)
    index2 = (xl64 & np.uint64(0xFF)).astype(jnp.int32)
    ll = ll_tbl[index2]
    return (iexpon << np.uint64(44)) + ((lh + ll) >> np.uint64(4))


def straw2_negdraw(x, item_id, r, weight):
    """Negated straw2 draw (uint64); smaller wins, first index on ties.

    ``weight`` is the 16.16 fixed-point u32 item weight; zero weight
    yields UINT64_MAX (never selected unless all weights are zero).
    """
    u = crush_hash32_3(x, item_id, r) & np.uint32(0xFFFF)
    ln_neg = (np.uint64(1) << np.uint64(48)) - crush_ln(u)
    w = jnp.maximum(_u32(weight), np.uint32(1)).astype(jnp.uint64)
    nd = ln_neg // w
    return jnp.where(_u32(weight) == 0, U64_MAX, nd)


def mulhi64(a, b):
    """High 64 bits of the 128-bit product of two uint64 arrays.

    Decomposed into 32-bit partial products (XLA emulates u64 on TPU
    with 32-bit pairs anyway; this keeps everything in plain muls/adds
    instead of a 128-bit path that doesn't exist)."""
    a = jnp.asarray(a, jnp.uint64)
    b = jnp.asarray(b, jnp.uint64)
    m32 = np.uint64(0xFFFFFFFF)
    s32 = np.uint64(32)
    a0 = a & m32
    a1 = a >> s32
    b0 = b & m32
    b1 = b >> s32
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = p01 + (p00 >> s32)  # <= (2^32-1)^2 + 2^32-1 < 2^64: no carry
    mid2 = mid + (p10 & m32)  # may carry
    carry = (mid2 < mid).astype(jnp.uint64)
    return p11 + (p10 >> s32) + (mid2 >> s32) + (carry << s32)


def magic_reciprocal(weight: np.ndarray) -> np.ndarray:
    """Host-precomputed M = floor((2^64-1)/w) per 16.16 weight (u64).

    Zero weights use the w=1 reciprocal (their lanes are masked to
    U64_MAX by the caller anyway).  Computed ONCE per map on the host
    so the straw2 hot loop never divides on device (TPU u64 division
    is an expensive emulation).
    """
    w = np.asarray(weight, np.uint64)
    w_safe = np.maximum(w, 1)
    return ((np.uint64(0xFFFFFFFFFFFFFFFF)) // w_safe).astype(np.uint64)


def div_by_magic(a, magic, w):
    """Exact floor(a / w) via the precomputed reciprocal.

    Valid for a < 2^50 (straw2's ln_neg <= 2^48): the mulhi estimate
    undershoots by < 3, fixed with three correction steps.  Bit-exact
    against the plain ``//`` path (differentially tested).
    """
    a = jnp.asarray(a, jnp.uint64)
    w = jnp.asarray(w, jnp.uint64)
    q = mulhi64(a, magic)
    rem = a - q * w
    for _ in range(3):
        over = rem >= w
        q = q + over.astype(jnp.uint64)
        rem = jnp.where(over, rem - w, rem)
    return q


def straw2_negdraw_magic(x, item_id, r, weight, magic):
    """straw2_negdraw with the division replaced by the hoisted magic
    reciprocal (bit-exact, device-division-free)."""
    u = crush_hash32_3(x, item_id, r) & np.uint32(0xFFFF)
    ln_neg = (np.uint64(1) << np.uint64(48)) - crush_ln(u)
    w = jnp.maximum(_u32(weight), np.uint32(1)).astype(jnp.uint64)
    nd = div_by_magic(ln_neg, jnp.asarray(magic, jnp.uint64), w)
    return jnp.where(_u32(weight) == 0, U64_MAX, nd)


def is_out(weight_osd, item, x):
    """Vectorized reweight rejection (True = rejected)."""
    w = _u32(weight_osd)
    h = crush_hash32_2(x, item) & np.uint32(0xFFFF)
    return jnp.where(w >= 0x10000, False, jnp.where(w == 0, True, h >= w))
