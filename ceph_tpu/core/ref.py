"""Pure-Python integer oracle for the CRUSH core primitives.

Bit-exact, scalar, slow.  This is the semantic ground truth the JAX path
and the C++ CPU reference are differentially tested against.  Semantics
follow the CRUSH spec recorded in SURVEY.md §2.1 (upstream layout:
``src/crush/hash.c :: crush_hash32_rjenkins1_{2,3}``,
``src/crush/mapper.c :: crush_ln / bucket_straw2_choose``,
``src/common/ceph_hash.cc :: ceph_str_hash_rjenkins``,
``src/include/rados.h :: ceph_stable_mod``).
"""

from __future__ import annotations

from ._crush_ln_tables import LL_TBL, RH_LH_TBL

M32 = 0xFFFFFFFF
CRUSH_HASH_SEED = 1315423911  # 0x4e67c6a7
S64_MIN = -(1 << 63)


def hashmix(a: int, b: int, c: int) -> tuple[int, int, int]:
    """One 9-line rjenkins mix round over wrapping u32."""
    a = (a - b - c) & M32
    a ^= c >> 13
    b = (b - c - a) & M32
    b = (b ^ (a << 8)) & M32
    c = (c - a - b) & M32
    c ^= b >> 13
    a = (a - b - c) & M32
    a ^= c >> 12
    b = (b - c - a) & M32
    b = (b ^ (a << 16)) & M32
    c = (c - a - b) & M32
    c ^= b >> 5
    a = (a - b - c) & M32
    a ^= c >> 3
    b = (b - c - a) & M32
    b = (b ^ (a << 10)) & M32
    c = (c - a - b) & M32
    c ^= b >> 15
    return a, b, c


def crush_hash32_2(a: int, b: int) -> int:
    a &= M32
    b &= M32
    h = (CRUSH_HASH_SEED ^ a ^ b) & M32
    x, y = 231232, 1232
    a, b, h = hashmix(a, b, h)
    x, a, h = hashmix(x, a, h)
    b, y, h = hashmix(b, y, h)
    return h


def crush_hash32_3(a: int, b: int, c: int) -> int:
    a &= M32
    b &= M32
    c &= M32
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c) & M32
    x, y = 231232, 1232
    a, b, h = hashmix(a, b, h)
    c, x, h = hashmix(c, x, h)
    y, a, h = hashmix(y, a, h)
    b, x, h = hashmix(b, x, h)
    y, c, h = hashmix(y, c, h)
    return h


def ceph_str_hash_rjenkins(data: bytes) -> int:
    """rjenkins over a byte string (object-name -> placement seed)."""
    length = len(data)
    a = b = 0x9E3779B9
    c = 0
    k = 0
    n = length
    while n >= 12:
        a = (a + int.from_bytes(data[k : k + 4], "little")) & M32
        b = (b + int.from_bytes(data[k + 4 : k + 8], "little")) & M32
        c = (c + int.from_bytes(data[k + 8 : k + 12], "little")) & M32
        a, b, c = hashmix(a, b, c)
        k += 12
        n -= 12
    c = (c + length) & M32
    if n >= 11:
        c = (c + (data[k + 10] << 24)) & M32
    if n >= 10:
        c = (c + (data[k + 9] << 16)) & M32
    if n >= 9:
        c = (c + (data[k + 8] << 8)) & M32
    if n >= 8:
        b = (b + (data[k + 7] << 24)) & M32
    if n >= 7:
        b = (b + (data[k + 6] << 16)) & M32
    if n >= 6:
        b = (b + (data[k + 5] << 8)) & M32
    if n >= 5:
        b = (b + data[k + 4]) & M32
    if n >= 4:
        a = (a + (data[k + 3] << 24)) & M32
    if n >= 3:
        a = (a + (data[k + 2] << 16)) & M32
    if n >= 2:
        a = (a + (data[k + 1] << 8)) & M32
    if n >= 1:
        a = (a + data[k]) & M32
    a, b, c = hashmix(a, b, c)
    return c


def ceph_str_hash_linux(data: bytes) -> int:
    """Linux dcache string hash (reference ``ceph_str_hash_linux``,
    ``src/common/ceph_hash.cc``): the alternate ``object_hash``
    selectable per pool (CEPH_STR_HASH_LINUX)."""
    h = 0
    for byte in data:
        h = (h + (byte << 4) + (byte >> 4)) * 11 & M32
    return h


# reference src/include/rados.h values — LINUX is 0x1, RJENKINS 0x2
CEPH_STR_HASH_LINUX = 1
CEPH_STR_HASH_RJENKINS = 2


def ceph_str_hash(alg: int, data: bytes) -> int:
    """Dispatch by pool ``object_hash`` id (reference ``ceph_str_hash``)."""
    if alg == CEPH_STR_HASH_LINUX:
        return ceph_str_hash_linux(data)
    if alg == CEPH_STR_HASH_RJENKINS:
        return ceph_str_hash_rjenkins(data)
    raise ValueError(f"unknown object_hash {alg}")


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    """Split-friendly bucketing for non-power-of-two moduli."""
    if (x & bmask) < b:
        return x & bmask
    return x & (bmask >> 1)


def pg_num_mask(pg_num: int) -> int:
    """Smallest 2^k - 1 >= pg_num - 1 (upstream calc_pg_masks semantics)."""
    return (1 << (pg_num - 1).bit_length()) - 1 if pg_num > 1 else 0


def crush_ln(xin: int) -> int:
    """~ 2^44 * log2(xin + 1) for xin in [0, 0xffff]; 48-bit fixed point."""
    x = xin + 1
    iexpon = 15
    if not (x & 0x18000):
        p = x.bit_length() - 1  # position of the highest set bit
        bits = 15 - p
        x <<= bits
        iexpon = p
    index1 = (x >> 8) << 1
    rh = RH_LH_TBL[index1 - 256]
    lh = RH_LH_TBL[index1 + 1 - 256]
    xl64 = (x * rh) >> 48
    index2 = xl64 & 0xFF
    ll = LL_TBL[index2]
    return (iexpon << 44) + ((lh + ll) >> 4)


def straw2_draw(x: int, item_id: int, r: int, weight: int) -> int:
    """Signed straw2 draw for one item.  weight is 16.16 fixed point u32."""
    if weight == 0:
        return S64_MIN
    u = crush_hash32_3(x, item_id, r) & 0xFFFF
    ln = crush_ln(u) - (1 << 48)  # <= 0
    # div64_s64 truncates toward zero; ln <= 0, weight > 0.
    return -((-ln) // weight)


def bucket_straw2_choose(
    item_ids: list[int], weights: list[int], x: int, r: int
) -> int:
    """Index (not id) of the straw2 winner; ties -> first index."""
    high = 0
    high_draw = 0
    for i, (iid, w) in enumerate(zip(item_ids, weights)):
        draw = straw2_draw(x, iid, r, w)
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return high


def is_out(weight_osd: int, item: int, x: int) -> bool:
    """Reweight rejection test; weight_osd is the 16.16 per-OSD reweight."""
    if weight_osd >= 0x10000:
        return False
    if weight_osd == 0:
        return True
    return (crush_hash32_2(x, item) & 0xFFFF) >= weight_osd
