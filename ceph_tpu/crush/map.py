"""CRUSH map model: hierarchy, rules, tunables, and dense packing.

The mutable Python model plays the role of the reference's CrushWrapper
mutation/serialization API (upstream ``src/crush/CrushWrapper.{h,cc}`` --
add_bucket / insert_item / adjust_item_weight / rule management /
tunable profiles), re-designed for a TPU pipeline: a map is *compiled*
(``to_dense``) into flat dense arrays -- the form both the C++ CPU
reference and the JAX interpreter consume -- rather than walked through
pointers.

Weights are 16.16 fixed point u32 (0x10000 == 1.0) exactly as in the
spec; bucket ids are negative, devices (OSDs) non-negative.
"""

from __future__ import annotations

import itertools
import json
from dataclasses import dataclass, field, asdict

import numpy as np

ITEM_NONE = 0x7FFFFFFF

ALG_UNIFORM = 1
ALG_LIST = 2
ALG_TREE = 3
ALG_STRAW = 4
ALG_STRAW2 = 5

ALG_NAMES = {
    ALG_UNIFORM: "uniform",
    ALG_LIST: "list",
    ALG_TREE: "tree",
    ALG_STRAW: "straw",
    ALG_STRAW2: "straw2",
}
ALG_IDS = {v: k for k, v in ALG_NAMES.items()}

# Rule step opcodes (shared with cpp/crush_ref.cpp :: RuleStep).
OP_TAKE = 1
OP_CHOOSE_FIRSTN = 2
OP_CHOOSE_INDEP = 3
OP_CHOOSELEAF_FIRSTN = 4
OP_CHOOSELEAF_INDEP = 5
OP_EMIT = 6
OP_SET_CHOOSE_TRIES = 7
OP_SET_CHOOSELEAF_TRIES = 8
OP_SET_CHOOSE_LOCAL_TRIES = 9
OP_SET_CHOOSE_LOCAL_FALLBACK_TRIES = 10
OP_SET_CHOOSELEAF_VARY_R = 11
OP_SET_CHOOSELEAF_STABLE = 12


@dataclass(frozen=True)
class Tunables:
    """Retry/stability knobs (upstream ``crush_map`` fields, crush.h)."""

    choose_total_tries: int = 50
    choose_local_tries: int = 0
    choose_local_fallback_tries: int = 0
    chooseleaf_descend_once: int = 1
    chooseleaf_vary_r: int = 1
    chooseleaf_stable: int = 1

    @staticmethod
    def profile(name: str) -> "Tunables":
        profiles = {
            # historical profiles; jewel == optimal == default
            "legacy": Tunables(19, 2, 5, 0, 0, 0),
            "argonaut": Tunables(19, 2, 5, 0, 0, 0),
            "bobtail": Tunables(50, 0, 0, 1, 0, 0),
            "firefly": Tunables(50, 0, 0, 1, 1, 0),
            "hammer": Tunables(50, 0, 0, 1, 1, 0),
            "jewel": Tunables(50, 0, 0, 1, 1, 1),
            "optimal": Tunables(50, 0, 0, 1, 1, 1),
            "default": Tunables(50, 0, 0, 1, 1, 1),
        }
        return profiles[name]


@dataclass
class Step:
    op: int
    arg1: int = 0
    arg2: int = 0


@dataclass
class Bucket:
    id: int  # negative
    name: str
    type_id: int
    alg: int = ALG_STRAW2
    items: list[int] = field(default_factory=list)
    item_weights: list[int] = field(default_factory=list)  # 16.16

    @property
    def weight(self) -> int:
        return sum(self.item_weights)


@dataclass
class Rule:
    id: int
    name: str
    kind: str = "replicated"  # or "erasure"
    steps: list[Step] = field(default_factory=list)


class CrushMap:
    """Mutable CRUSH map with a CrushWrapper-parity mutation API."""

    _uid_counter = itertools.count(1)

    def __init__(self, tunables: Tunables | None = None):
        self.tunables = tunables or Tunables.profile("default")
        self.types: dict[int, str] = {0: "osd"}
        self.buckets: dict[int, Bucket] = {}  # id (negative) -> bucket
        self.rules: dict[int, Rule] = {}
        self.device_names: dict[int, str] = {}  # osd id -> name
        self.device_classes: dict[int, str] = {}  # osd id -> class name
        # (uid, version) identifies map content for compile caches: uid
        # is process-unique (never reused, unlike id()), version bumps
        # on every API mutation.  Direct field edits bypass it —
        # mutate through the API.
        self.uid = next(CrushMap._uid_counter)
        self.version = 0
        self._dense_cache: dict = {}  # keyed (version, choose_args name)
        # per-pool alternate weight sets (reference crush_choose_arg /
        # CrushWrapper::choose_args, the crush-compat balancer's lever):
        # name -> {bucket_id -> [alt item weights]}
        self.choose_args: dict[str, dict[int, list[int]]] = {}
        self._shadow_of: dict[int, tuple[int, str]] = {}

    def _mutated(self) -> None:
        self.version += 1
        self._dense_cache = {}

    def set_tunables(self, tunables: Tunables | str) -> None:
        """Switch tunables (profile name or explicit Tunables); the API
        route so caches invalidate."""
        if isinstance(tunables, str):
            tunables = Tunables.profile(tunables)
        self.tunables = tunables
        self._mutated()

    def __getstate__(self):
        d = self.__dict__.copy()
        d["_dense_cache"] = {}  # not worth copying/pickling
        return d

    def __deepcopy__(self, memo):
        import copy as _copy

        new = CrushMap.__new__(CrushMap)
        memo[id(self)] = new
        state = self.__getstate__()
        new.__dict__.update(_copy.deepcopy(state, memo))
        # a copy is a distinct map for cache purposes
        new.uid = next(CrushMap._uid_counter)
        return new

    # ---- types ----

    def add_type(self, type_id: int, name: str) -> None:
        self.types[type_id] = name
        self._mutated()

    def type_id(self, name: str) -> int:
        for tid, tname in self.types.items():
            if tname == name:
                return tid
        raise KeyError(name)

    # ---- devices ----

    def add_device(self, osd: int, name: str | None = None, device_class: str | None = None) -> None:
        self.device_names[osd] = name or f"osd.{osd}"
        if device_class is not None:
            self.device_classes[osd] = device_class
        self._mutated()

    @property
    def max_devices(self) -> int:
        ids = list(self.device_names)
        for b in self.buckets.values():
            ids.extend(i for i in b.items if i >= 0)
        return max(ids, default=-1) + 1

    # ---- buckets ----

    def add_bucket(
        self,
        name: str,
        type_name: str,
        alg: int = ALG_STRAW2,
        bucket_id: int | None = None,
    ) -> Bucket:
        if bucket_id is None:
            bucket_id = min(self.buckets, default=0) - 1
        if bucket_id >= 0 or bucket_id in self.buckets:
            raise ValueError(f"bad bucket id {bucket_id}")
        if any(b.name == name for b in self.buckets.values()):
            raise ValueError(f"duplicate bucket name {name}")
        b = Bucket(id=bucket_id, name=name, type_id=self.type_id(type_name), alg=alg)
        self.buckets[bucket_id] = b
        self._mutated()
        return b

    def bucket_by_name(self, name: str) -> Bucket:
        for b in self.buckets.values():
            if b.name == name:
                return b
        raise KeyError(name)

    def item_name(self, item: int) -> str:
        if item >= 0:
            return self.device_names.get(item, f"osd.{item}")
        return self.buckets[item].name

    def insert_item(self, bucket_id: int, item: int, weight: int) -> None:
        """Add item (device >= 0 or bucket < 0) with 16.16 weight."""
        b = self.buckets[bucket_id]
        if item in b.items:
            raise ValueError(f"item {item} already in bucket {b.name}")
        if item >= 0 and item not in self.device_names:
            self.add_device(item)
        b.items.append(item)
        b.item_weights.append(int(weight))
        self._mutated()

    def remove_item(self, bucket_id: int, item: int) -> None:
        b = self.buckets[bucket_id]
        i = b.items.index(item)
        del b.items[i]
        del b.item_weights[i]
        self._mutated()

    def adjust_item_weight(self, bucket_id: int, item: int, weight: int) -> None:
        b = self.buckets[bucket_id]
        b.item_weights[b.items.index(item)] = int(weight)
        self._mutated()

    def adjust_subtree_weights(self, bucket_id: int) -> int:
        """Recompute this subtree's item weights bottom-up; returns total."""
        b = self.buckets[bucket_id]
        self._mutated()
        total = 0
        for i, item in enumerate(b.items):
            if item < 0:
                b.item_weights[i] = self.adjust_subtree_weights(item)
            total += b.item_weights[i]
        return total

    def parent_of(self, item: int) -> int | None:
        for b in self.buckets.values():
            if item in b.items:
                return b.id
        return None

    # ---- rules ----

    def add_rule(self, name: str, steps: list[Step], kind: str = "replicated", rule_id: int | None = None) -> Rule:
        if rule_id is None:
            rule_id = max(self.rules, default=-1) + 1
        r = Rule(id=rule_id, name=name, kind=kind, steps=steps)
        self.rules[rule_id] = r
        self._mutated()
        return r

    def rule_by_name(self, name: str) -> Rule:
        for r in self.rules.values():
            if r.name == name:
                return r
        raise KeyError(name)

    def make_replicated_rule(
        self,
        name: str,
        root: str,
        failure_domain: str,
        device_class: str | None = None,
    ) -> Rule:
        """`take root [class X]; chooseleaf firstn 0 type fd; emit`."""
        root_id = self._resolve_take(root, device_class)
        fd = self.type_id(failure_domain)
        steps = [Step(OP_TAKE, root_id), Step(OP_CHOOSELEAF_FIRSTN, 0, fd), Step(OP_EMIT)]
        return self.add_rule(name, steps)

    def make_erasure_rule(
        self,
        name: str,
        root: str,
        failure_domain: str,
        device_class: str | None = None,
    ) -> Rule:
        root_id = self._resolve_take(root, device_class)
        fd = self.type_id(failure_domain)
        steps = [
            Step(OP_SET_CHOOSELEAF_TRIES, 5),
            Step(OP_TAKE, root_id),
            Step(OP_CHOOSELEAF_INDEP, 0, fd) if fd != 0 else Step(OP_CHOOSE_INDEP, 0, 0),
            Step(OP_EMIT),
        ]
        return self.add_rule(name, steps, kind="erasure")

    def _resolve_take(self, root: str, device_class: str | None) -> int:
        if device_class is None:
            return self.bucket_by_name(root).id
        return self.class_shadow_root(
            self.bucket_by_name(root).id, device_class
        )

    # ---- device-class shadow trees ----
    #
    # Reference semantics (CrushWrapper::populate_classes /
    # device_class_clone): a rule's `take <root> class <c>` resolves to
    # a per-class clone of the subtree containing only the devices of
    # that class, buckets named `<name>~<c>`, with weights re-summed.
    # Shadow trees are rebuilt on demand and tracked so decompile can
    # print the class form.

    def class_shadow_root(self, root_id: int, device_class: str) -> int:
        shadow = self._build_class_shadow(root_id, device_class)
        if shadow is None:
            raise ValueError(
                f"no devices of class {device_class!r} under "
                f"{self.buckets[root_id].name}"
            )
        return shadow

    def shadow_origin(self, bucket_id: int) -> tuple[int, str] | None:
        """(original bucket id, class) if bucket_id is a shadow."""
        return getattr(self, "_shadow_of", {}).get(bucket_id)

    def _build_class_shadow(self, bid: int, cls: str) -> int | None:
        if not hasattr(self, "_shadow_of"):
            self._shadow_of: dict[int, tuple[int, str]] = {}
        b = self.buckets[bid]
        shadow_name = f"{b.name}~{cls}"
        keep_id = None
        try:
            existing = self.bucket_by_name(shadow_name)
            # rebuild in place (weights may have changed), keeping the
            # id stable so rules referencing the shadow stay valid
            keep_id = existing.id
            del self.buckets[existing.id]
            self._shadow_of.pop(existing.id, None)
            self._mutated()
        except KeyError:
            pass
        items: list[int] = []
        weights: list[int] = []
        for item, w in zip(b.items, b.item_weights):
            if item >= 0:
                if self.device_classes.get(item) == cls:
                    items.append(item)
                    weights.append(w)
            else:
                sub = self._build_class_shadow(item, cls)
                if sub is not None:
                    items.append(sub)
                    weights.append(self.buckets[sub].weight)
        if not items:
            return None
        sb = self.add_bucket(
            shadow_name, self.types[b.type_id], alg=b.alg, bucket_id=keep_id
        )
        for item, w in zip(items, weights):
            self.insert_item(sb.id, item, w)
        self._shadow_of[sb.id] = (bid, cls)
        return sb.id

    # ---- hierarchy queries ----

    def max_depth(self) -> int:
        """Longest bucket chain (root bucket -> ... -> device edge count)."""

        def depth(bid: int) -> int:
            b = self.buckets[bid]
            sub = [depth(i) for i in b.items if i < 0]
            return 1 + max(sub, default=0)

        roots = [bid for bid in self.buckets if self.parent_of(bid) is None]
        return max((depth(r) for r in roots), default=0)

    # ---- serialization (framework-native, versioned JSON) ----

    def to_obj(self) -> dict:
        return {
            "version": 1,
            "tunables": asdict(self.tunables),
            "types": self.types,
            "devices": {str(k): v for k, v in self.device_names.items()},
            "device_classes": {str(k): v for k, v in self.device_classes.items()},
            "buckets": [
                {
                    "id": b.id,
                    "name": b.name,
                    "type_id": b.type_id,
                    "alg": b.alg,
                    "items": b.items,
                    "item_weights": b.item_weights,
                }
                for b in self.buckets.values()
            ],
            "rules": [
                {
                    "id": r.id,
                    "name": r.name,
                    "kind": r.kind,
                    "steps": [[s.op, s.arg1, s.arg2] for s in r.steps],
                }
                for r in self.rules.values()
            ],
            "choose_args": {
                name: {str(bid): w for bid, w in per.items()}
                for name, per in self.choose_args.items()
            },
            "shadow_of": {
                str(sid): [orig, cls]
                for sid, (orig, cls) in self._shadow_of.items()
            },
        }

    def encode(self) -> bytes:
        return json.dumps(self.to_obj(), sort_keys=True).encode()

    @staticmethod
    def from_obj(obj: dict) -> "CrushMap":
        m = CrushMap(Tunables(**obj["tunables"]))
        m.types = {int(k): v for k, v in obj["types"].items()}
        m.device_names = {int(k): v for k, v in obj["devices"].items()}
        m.device_classes = {int(k): v for k, v in obj.get("device_classes", {}).items()}
        for bo in obj["buckets"]:
            b = Bucket(
                id=bo["id"],
                name=bo["name"],
                type_id=bo["type_id"],
                alg=bo["alg"],
                items=list(bo["items"]),
                item_weights=list(bo["item_weights"]),
            )
            m.buckets[b.id] = b
        for ro in obj["rules"]:
            m.rules[ro["id"]] = Rule(
                id=ro["id"],
                name=ro["name"],
                kind=ro["kind"],
                steps=[Step(*s) for s in ro["steps"]],
            )
        m.choose_args = {
            name: {int(bid): list(w) for bid, w in per.items()}
            for name, per in obj.get("choose_args", {}).items()
        }
        m._shadow_of = {
            int(sid): (orig, cls)
            for sid, (orig, cls) in obj.get("shadow_of", {}).items()
        }
        m._mutated()
        return m

    @staticmethod
    def decode(data: bytes) -> "CrushMap":
        return CrushMap.from_obj(json.loads(data.decode()))

    # ---- choose_args (alternate weight sets) ----

    def create_choose_args(self, name: str) -> dict[int, list[int]]:
        """New weight-set initialized from the current bucket weights."""
        per = {bid: list(b.item_weights) for bid, b in self.buckets.items()}
        self.choose_args[name] = per
        self._mutated()
        return per

    def rm_choose_args(self, name: str) -> None:
        self.choose_args.pop(name, None)
        self._mutated()

    def choose_args_name_for_pool(self, pool_id: int) -> str | None:
        """Weight-set placement resolution (upstream ``do_rule`` picks
        choose_args by pool id, falling back to the compat set)."""
        if str(pool_id) in self.choose_args:
            return str(pool_id)
        if "compat" in self.choose_args:
            return "compat"
        return None

    def choose_args_adjust_item_weight(
        self, name: str, bucket_id: int, item: int, weight: int
    ) -> None:
        b = self.buckets[bucket_id]
        self.choose_args[name][bucket_id][b.items.index(item)] = int(weight)
        self._mutated()

    # ---- dense packing ----

    def to_dense(self, choose_args: str | None = None) -> "DenseCrushMap":
        # small dict, not a single slot: with per-pool weight sets the
        # host placement path alternates choose_args names per pool and
        # a one-entry cache would rebuild the dense map per PG lookup
        key = (self.version, choose_args)
        cached = self._dense_cache.get(key)
        if cached is not None:
            return cached
        if len(self._dense_cache) >= 8 or (
            self._dense_cache and next(iter(self._dense_cache))[0] != self.version
        ):
            self._dense_cache.clear()  # stale version or cap reached
        dense = self._to_dense(choose_args)
        self._dense_cache[key] = dense
        return dense

    def _to_dense(self, choose_args: str | None = None) -> "DenseCrushMap":
        n_buckets = max((-bid for bid in self.buckets), default=0)
        max_fanout = max((len(b.items) for b in self.buckets.values()), default=1)
        max_fanout = max(max_fanout, 1)
        override = self.choose_args.get(choose_args, {}) if choose_args else {}
        alg = np.zeros(n_buckets, np.int32)
        btype = np.zeros(n_buckets, np.int32)
        size = np.zeros(n_buckets, np.int32)
        items = np.zeros((n_buckets, max_fanout), np.int32)
        weights = np.zeros((n_buckets, max_fanout), np.uint32)
        for bid, b in self.buckets.items():
            i = -1 - bid
            alg[i] = b.alg
            btype[i] = b.type_id
            size[i] = len(b.items)
            items[i, : len(b.items)] = b.items
            w = override.get(bid, b.item_weights)
            if len(w) != len(b.items):  # stale weight-set row: fall back
                w = b.item_weights
            weights[i, : len(b.items)] = w
        from .legacy import aux_arrays

        aux = aux_arrays(alg, size, weights)  # None unless legacy algs
        scaled, tree_w, max_nodes = aux if aux is not None else (None, None, 0)
        return DenseCrushMap(
            n_buckets=n_buckets,
            max_fanout=max_fanout,
            max_devices=self.max_devices,
            max_depth=self.max_depth(),
            tunables=self.tunables,
            alg=alg,
            btype=btype,
            size=size,
            items=items,
            weights=weights,
            scaled=scaled,
            tree_weights=tree_w,
            max_tree_nodes=max_nodes,
        )


@dataclass
class DenseCrushMap:
    """Flat dense form consumed by the C++ reference and the JAX path."""

    n_buckets: int
    max_fanout: int
    max_devices: int
    max_depth: int
    tunables: Tunables
    alg: np.ndarray  # [n_buckets] int32
    btype: np.ndarray  # [n_buckets] int32
    size: np.ndarray  # [n_buckets] int32
    items: np.ndarray  # [n_buckets, max_fanout] int32
    weights: np.ndarray  # [n_buckets, max_fanout] uint32
    # legacy-alg derived state (upstream builder.c), present only when a
    # list/straw1/tree bucket exists: per-item straws (straw1) or prefix
    # sums (list) packed in one table, plus tree node weights
    scaled: np.ndarray | None = None  # [n_buckets, max_fanout] uint32
    tree_weights: np.ndarray | None = None  # [n_buckets, max_tree_nodes] u32
    max_tree_nodes: int = 0

    def algs_present(self) -> set[int]:
        return set(int(a) for a in np.unique(self.alg[self.size > 0]))

    def legacy_algs_present(self) -> set[int]:
        return self.algs_present() & {ALG_LIST, ALG_TREE, ALG_STRAW}
