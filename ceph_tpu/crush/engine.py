"""Engine dispatch: pick the fastest CRUSH batch executor for a map+rule.

Two device engines implement identical placement semantics (upstream
``src/crush/mapper.c :: crush_do_rule``):

- :mod:`ceph_tpu.crush.interp_batch` — level-synchronous, one-hot-MXU
  engine (the fast path; straw2 maps with modern tunables), and
- :mod:`ceph_tpu.crush.interp` — the general ``vmap`` engine (uniform
  buckets, legacy shapes; single choose step per take).

A third tier guarantees reference semantics for every remaining shape:
the in-repo C++ reference (:mod:`ceph_tpu.testing.cppref`, a native
implementation of the upstream working-vector loop).  Rules land there
only when no device engine is exact — today that is chained choose
steps whose per-step fan-out overflows ``result_max`` (where the
reference caps each inner choose by the lane's remaining space,
dynamically), chained chooses on maps the fast engine rejects, and
maps containing legacy list/tree/straw1 buckets (whose sequential /
float-derived semantics no device engine implements).

Callers that just want "run this rule for a batch of x" should go
through :func:`make_batch_runner` / :func:`run_batch` so they always
get reference semantics at the fastest qualifying tier.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from . import interp, interp_batch
from .map import (
    DenseCrushMap,
    OP_CHOOSE_FIRSTN,
    OP_CHOOSE_INDEP,
    OP_CHOOSELEAF_FIRSTN,
    OP_CHOOSELEAF_INDEP,
    OP_EMIT,
    OP_TAKE,
    Rule,
)

_CHOOSE_OPS = (
    OP_CHOOSE_FIRSTN,
    OP_CHOOSE_INDEP,
    OP_CHOOSELEAF_FIRSTN,
    OP_CHOOSELEAF_INDEP,
)


def _chain_overflows(rule: Rule, result_max: int) -> bool:
    """Static check: does any chained choose's fan-out exceed
    ``result_max``?  In that regime the reference caps each inner choose
    by the lane's *dynamic* remaining space (``result_max - osize``,
    mapper.c crush_do_rule), which the batch engine cannot express with
    static shapes — it raises instead of deviating."""
    width = 0
    for s in rule.steps:
        if s.op == OP_TAKE:
            width = 1
        elif s.op in _CHOOSE_OPS:
            numrep = s.arg1 if s.arg1 > 0 else s.arg1 + result_max
            if numrep <= 0:
                continue
            if width > 1 and width * numrep > result_max:
                return True
            width = min(width * numrep, result_max)
        elif s.op == OP_EMIT:
            width = 0
    return False


def _interp_supports(rule: Rule) -> bool:
    """The vmap engine runs single-choose-per-take programs only
    (its working vector holds one pending take, not a chain)."""
    pending = False  # an un-consumed choose result in the working vector
    for s in rule.steps:
        if s.op == OP_TAKE:
            pending = False
        elif s.op in _CHOOSE_OPS:
            if pending:
                return False
            pending = True
        elif s.op == OP_EMIT:
            pending = False
    return True


def _host_runner(dense: DenseCrushMap, rule: Rule, result_max: int):
    """Exact-semantics native fallback on the C++ reference tier.

    The map travels through ``crush_arg`` (here the DenseCrushMap
    itself), NOT a closure: signature-keyed fn caches reuse ``fn``
    across maps sharing a signature, so baking the map in would serve
    stale placements (see test_compile_cache_distinguishes_same_shape_maps).
    """
    from ceph_tpu.testing import cppref

    steps = [(s.op, s.arg1, s.arg2) for s in rule.steps]

    def fn(dense_arg, osd_weight, xs):
        res, lens = cppref.do_rule_batch(
            dense_arg, steps,
            np.asarray(xs, np.uint32),
            np.asarray(osd_weight, np.uint32),
            result_max,
        )
        return jnp.asarray(res), jnp.asarray(lens)

    return dense, fn


def make_batch_runner(dense: DenseCrushMap, rule: Rule, result_max: int):
    """Return ``(crush_arg, fn)`` with ``fn(crush_arg, osd_weight, xs)
    -> (results [n, result_max] i32, lens [n] i32)``.

    ``crush_arg`` is a pytree of device arrays (per-level packs for the
    fast engine, the dense map for the general one); it is a traced
    argument of ``fn``, so maps sharing topology shape reuse compiled
    programs.
    """
    if interp_batch.supports(dense, rule) and not _chain_overflows(
        rule, result_max
    ):
        return interp_batch.fast_runner(dense, rule, result_max)
    if _interp_supports(rule) and not dense.legacy_algs_present():
        smap = interp.StaticCrushMap(dense)
        return smap, interp.batch_runner(smap, rule, result_max)
    return _host_runner(dense, rule, result_max)


def runner_signature(dense: DenseCrushMap, rule: Rule, result_max: int) -> tuple:
    """Hashable static signature of the program make_batch_runner would
    build — equal signatures share one compiled executable."""
    if interp_batch.supports(dense, rule) and not _chain_overflows(
        rule, result_max
    ):
        return ("fast",) + interp_batch.fast_signature(dense, rule, result_max)
    if not _interp_supports(rule) or dense.legacy_algs_present():
        return ("host", interp.rule_signature(rule), result_max)
    # smap_signature's fields, read straight off the dense map (no
    # StaticCrushMap construction — that would upload the whole map)
    return (
        "vmap",
        (
            dense.n_buckets,
            dense.max_fanout,
            dense.max_devices,
            max(dense.max_depth, 1),
            dense.tunables,
            frozenset(dense.algs_present()),
        ),
        interp.rule_signature(rule),
        result_max,
    )


def run_batch(dense: DenseCrushMap, rule: Rule, xs, osd_weight, result_max: int):
    """One-shot batched rule execution on the best engine."""
    crush_arg, fn = make_batch_runner(dense, rule, result_max)
    return fn(crush_arg, jnp.asarray(osd_weight, jnp.uint32),
              jnp.asarray(xs, jnp.uint32))
