"""Engine dispatch: pick the fastest CRUSH batch executor for a map+rule.

Two device engines implement identical placement semantics (upstream
``src/crush/mapper.c :: crush_do_rule``):

- :mod:`ceph_tpu.crush.interp_batch` — level-synchronous, one-hot-MXU
  engine (the fast path; straw2 maps with modern tunables), and
- :mod:`ceph_tpu.crush.interp` — the general ``vmap`` engine (uniform
  buckets, legacy shapes).

Callers that just want "run this rule for a batch of x" should go
through :func:`make_batch_runner` / :func:`run_batch` so they get the
fast path whenever the map qualifies.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import interp, interp_batch
from .map import DenseCrushMap, Rule


def make_batch_runner(dense: DenseCrushMap, rule: Rule, result_max: int):
    """Return ``(crush_arg, fn)`` with ``fn(crush_arg, osd_weight, xs)
    -> (results [n, result_max] i32, lens [n] i32)``.

    ``crush_arg`` is a pytree of device arrays (per-level packs for the
    fast engine, the dense map for the general one); it is a traced
    argument of ``fn``, so maps sharing topology shape reuse compiled
    programs.
    """
    if interp_batch.supports(dense, rule):
        return interp_batch.fast_runner(dense, rule, result_max)
    smap = interp.StaticCrushMap(dense)
    return smap, interp.batch_runner(smap, rule, result_max)


def runner_signature(dense: DenseCrushMap, rule: Rule, result_max: int) -> tuple:
    """Hashable static signature of the program make_batch_runner would
    build — equal signatures share one compiled executable."""
    if interp_batch.supports(dense, rule):
        return ("fast",) + interp_batch.fast_signature(dense, rule, result_max)
    # smap_signature's fields, read straight off the dense map (no
    # StaticCrushMap construction — that would upload the whole map)
    return (
        "vmap",
        (
            dense.n_buckets,
            dense.max_fanout,
            dense.max_devices,
            max(dense.max_depth, 1),
            dense.tunables,
            frozenset(dense.algs_present()),
        ),
        interp.rule_signature(rule),
        result_max,
    )


def run_batch(dense: DenseCrushMap, rule: Rule, xs, osd_weight, result_max: int):
    """One-shot batched rule execution on the best engine."""
    crush_arg, fn = make_batch_runner(dense, rule, result_max)
    return fn(crush_arg, jnp.asarray(osd_weight, jnp.uint32),
              jnp.asarray(xs, jnp.uint32))
