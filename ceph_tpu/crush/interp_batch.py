"""Level-synchronous batched CRUSH interpreter (the fast TPU hot path).

Semantics: identical to :mod:`ceph_tpu.crush.interp` (itself differentially
tested against the in-repo C++ reference of upstream
``src/crush/mapper.c :: crush_do_rule / crush_choose_firstn /
crush_choose_indep``), but restructured batch-first for the TPU memory
system instead of ``vmap`` over a scalar program.

Why this module exists (round-3 profiling result): the ``vmap`` path's
per-lane dynamic gathers into bucket tables (``smap.items[bidx]`` with a
lane-varying ``bidx``) lower to TPU gathers that run ~30,000x slower
than the straw2 arithmetic around them — the whole reason BENCH_r02
measured 96 K placements/s against a >=6.25 M/s per-chip target.

Design:

- **One-hot MXU matmul instead of gathers.**  Every bucket-table row
  fetch is ``onehot(lidx) @ table`` in bf16 with f32 accumulation.  The
  tables are byte-split (one bf16 column per byte of each u32/u64
  field), which makes the matmul *exact*: each product is 0/1 x [0,255]
  and each output element sums exactly one nonzero term.  A row fetch
  for a 1M-lane batch costs ~0.05 ms on a v5e (MXU speed) versus
  ~40-1500 ms for the equivalent lowered gather.
- **Level-synchronous descent.**  All lanes walk one hierarchy level per
  step; levels are the BFS level sets of the map from the rule's take
  root, so each level's table holds only the buckets reachable at that
  depth (a single-bucket level is a broadcast row — no matmul at all).
- **Masked whole-batch retry rounds.**  The reference's per-replica
  retry ladder (r' = r + ftotal) becomes a ``lax.while_loop`` whose body
  re-descends the full batch with per-lane r; settled lanes are masked.
  P(retry) is small, so the expected round count is 1 + epsilon and each
  round is a handful of MXU launches.
- **General rule programs.**  Multi-TAKE chains and chained choose steps
  (``take ssd ... emit; take hdd ... emit``; ``choose rack 2; chooseleaf
  host 2``) run natively: each choose consumes the working vector
  entry-by-entry (statically unrolled; the working vector is at most
  ``result_max`` wide), like the reference's ``crush_do_rule``
  working-vector loop.  Working-vector bucket ids are translated to the
  next pack's local indices with a small one-hot over its root list.

Scope (checked by :func:`supports`): straw2 buckets only (uniform/list/
tree maps fall back to ``interp.batch_do_rule``), bobtail+ tunables (no
legacy local retries), take targets must be buckets.  Multi-EMIT
programs that overflow ``result_max`` drop surplus at emit via masked
writes — the same cap the reference's EMIT applies (``result_len <
result_max``), differentially pinned in ``tests/test_crush_batch.py``.
Chained chooses whose fan-out exceeds ``result_max`` would need the
reference's *dynamic* per-lane inner-choose cap (``result_max - osize``)
which static shapes cannot express: compile raises, and
``engine.make_batch_runner`` detects the shape statically and routes to
the exact C++ tier instead.
"""

from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ceph_tpu.core import hashes, pallas_straw2
from .interp import _memo_put, rule_signature  # shared memo policy
from .map import (
    ALG_STRAW2,
    ITEM_NONE,
    DenseCrushMap,
    OP_CHOOSE_FIRSTN,
    OP_CHOOSE_INDEP,
    OP_CHOOSELEAF_FIRSTN,
    OP_CHOOSELEAF_INDEP,
    OP_EMIT,
    OP_SET_CHOOSE_TRIES,
    OP_SET_CHOOSELEAF_TRIES,
    OP_SET_CHOOSE_LOCAL_TRIES,
    OP_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    OP_SET_CHOOSELEAF_VARY_R,
    OP_SET_CHOOSELEAF_STABLE,
    OP_TAKE,
    Rule,
)

I32 = jnp.int32
U32 = jnp.uint32
U64 = jnp.uint64

ITEM_UNDEF = 0x7FFFFFFE

_CHOOSE_OPS = (
    OP_CHOOSE_FIRSTN,
    OP_CHOOSE_INDEP,
    OP_CHOOSELEAF_FIRSTN,
    OP_CHOOSELEAF_INDEP,
)

# Byte-column layout per slot (role-major blocks of F columns each):
# id[4] weight[4] magic[8] child_type[1] next_lidx[2]  = 19 role bytes,
# plus 2 trailing per-row size bytes.
_SLOT_BYTES = 19
_OFF_ID = 0
_OFF_W = 4
_OFF_MAG = 8
_OFF_CTYPE = 16
_OFF_NLIDX = 17

# child_type sentinel for a dangling bucket reference (child idx out of
# range); real type ids are capped below this by supports()
_CTYPE_DANGLING = 255

# the fused descend kernel re-declares these sentinels (importing this
# module from core/ would cycle); keep them coupled
assert int(pallas_straw2.ITEM_NONE_U32) == ITEM_NONE
assert int(pallas_straw2._CT_DANGLING) == _CTYPE_DANGLING


class LevelTable:
    """One BFS level of a descent pack (pytree).

    Carries two device encodings of the same level: ``tb`` (byte-split
    bf16 for the XLA one-hot matmul path) and, when the level fits the
    Pallas level kernel's bounds, ``lane_tb`` ([6, F, H, 128] u32 lane
    vectors for in-VMEM dynamic_gather row fetch)."""

    def __init__(self, tb: jnp.ndarray, nb: int, fanout: int,
                 lane_tb: jnp.ndarray | None = None):
        self.tb = tb  # [NB, 19*F + 2] bfloat16 byte-split table
        self.nb = nb
        self.fanout = fanout
        self.lane_tb = lane_tb

    def tree_flatten(self):
        if self.lane_tb is None:
            return (self.tb,), (self.nb, self.fanout, False)
        return (self.tb, self.lane_tb), (self.nb, self.fanout, True)

    @classmethod
    def tree_unflatten(cls, static, arrays):
        nb, fanout, has_lane = static
        return cls(arrays[0], nb, fanout,
                   arrays[1] if has_lane else None)


jax.tree_util.register_pytree_node(
    LevelTable, lambda t: t.tree_flatten(), LevelTable.tree_unflatten
)


class DescendPack:
    """Per-level tables for one descent, as a pytree of LevelTables.

    When every level fits the Pallas bounds, also carries the stacked
    whole-descent table (``desc_tb`` [L, 6, Fmax, Hmax, 128] u32 +
    static ``desc_meta``) for the single-kernel descent path."""

    def __init__(self, tables: tuple[LevelTable, ...],
                 desc_tb: jnp.ndarray | None = None,
                 desc_meta: tuple | None = None):
        self.tables = tuple(tables)
        self.desc_tb = desc_tb
        self.desc_meta = desc_meta

    def tree_flatten(self):
        if self.desc_tb is None:
            return tuple(self.tables), (len(self.tables), None)
        return tuple(self.tables) + (self.desc_tb,), (
            len(self.tables), self.desc_meta)

    @classmethod
    def tree_unflatten(cls, static, arrays):
        n, desc_meta = static
        if desc_meta is None:
            return cls(tuple(arrays))
        return cls(tuple(arrays[:n]), arrays[n], desc_meta)

    @property
    def signature(self) -> tuple:
        return (tuple((t.nb, t.fanout) for t in self.tables),
                self.desc_meta)


jax.tree_util.register_pytree_node(
    DescendPack, lambda p: p.tree_flatten(), DescendPack.tree_unflatten
)


def _byte_cols(vals: np.ndarray, nbytes: int) -> list[np.ndarray]:
    """Little-endian byte planes of an unsigned array, as float32."""
    v = vals.astype(np.uint64)
    return [((v >> np.uint64(8 * i)) & np.uint64(0xFF)).astype(np.float32)
            for i in range(nbytes)]


def _build_level_table(
    dense: DenseCrushMap,
    bucket_idxs: list[int],
    next_map: dict[int, int],
    consumer_map: dict[int, int],
    target_type: int,
) -> LevelTable:
    """Byte-split table for one BFS level.

    ``next_map``: bucket idx -> local idx in this pack's next level.
    ``consumer_map``: bucket idx -> local idx at level 0 of the leaf
    pack (chooseleaf only).  A chosen child of ``target_type`` is
    consumed by the leaf pack; any other bucket child keeps descending
    in this pack, so one column serves both (usage is disjoint).
    """
    nb = max(len(bucket_idxs), 1)
    fanout = 1
    for b in bucket_idxs:
        fanout = max(fanout, int(dense.size[b]))
    ids = np.zeros((nb, fanout), np.uint32)
    ws = np.zeros((nb, fanout), np.uint32)
    ctype = np.zeros((nb, fanout), np.uint32)
    nlidx = np.zeros((nb, fanout), np.uint32)
    sizes = np.zeros((nb,), np.uint32)
    for row, b in enumerate(bucket_idxs):
        sz = int(dense.size[b])
        sizes[row] = sz
        for f in range(sz):
            item = int(dense.items[b, f])
            ids[row, f] = np.uint32(item & 0xFFFFFFFF)
            ws[row, f] = dense.weights[b, f]
            if item < 0:
                cidx = -1 - item
                if cidx < dense.n_buckets:
                    ct = int(dense.btype[cidx])
                    ctype[row, f] = ct
                    if ct == target_type and target_type != 0:
                        nlidx[row, f] = consumer_map.get(cidx, 0)
                    else:
                        nlidx[row, f] = next_map.get(cidx, 0)
                else:
                    # dangling bucket reference: descend() hard-fails on
                    # the sentinel (reference bad-bucket skip_rep;
                    # supports() guarantees real types stay < 255)
                    ctype[row, f] = _CTYPE_DANGLING
    magic = hashes.magic_reciprocal(ws)
    col_list = (
        _byte_cols(ids, 4)
        + _byte_cols(ws, 4)
        + _byte_cols(magic, 8)
        + _byte_cols(ctype, 1)
        + _byte_cols(nlidx, 2)
    )
    tb = np.concatenate(
        col_list + [c[:, None] for c in _byte_cols(sizes, 2)], axis=1
    )
    lane_np = None
    if _want_lane_tables():
        lane_np = pallas_straw2.pack_level_table(
            ids, ws, magic, ctype, nlidx, sizes)
    # lane_tb attachment is decided by build_pack: when the fused
    # whole-descent table is built, per-level device uploads are dead
    lt = LevelTable(jnp.asarray(tb, jnp.bfloat16), nb, fanout, None)
    return lt, lane_np


def _bfs_levels(
    dense: DenseCrushMap, roots: list[int], stop_type: int, max_levels: int
) -> list[list[int]]:
    """BFS level sets of bucket indices from ``roots``.  Children of
    buckets whose type is ``stop_type`` are not expanded beyond level 0
    (descent stops there)."""
    levels = [list(roots)]
    while len(levels) < max_levels:
        nxt: list[int] = []
        seen: set[int] = set()
        for b in levels[-1]:
            if (
                stop_type != 0
                and len(levels) > 1
                and int(dense.btype[b]) == stop_type
            ):
                continue
            for f in range(int(dense.size[b])):
                item = int(dense.items[b, f])
                if item < 0:
                    cidx = -1 - item
                    if cidx < dense.n_buckets and cidx not in seen:
                        seen.add(cidx)
                        nxt.append(cidx)
        if not nxt:
            break
        levels.append(nxt)
    return levels


def _stop_buckets(
    dense: DenseCrushMap, roots: list[int], target_type: int
) -> list[int]:
    """Reachable target-type buckets in BFS order — build_pack's stop
    list without constructing any tables."""
    levels = _bfs_levels(dense, roots, target_type, dense.max_depth + 2)
    stop: list[int] = []
    seen: set[int] = set()
    for lvl in levels:
        for b in lvl:
            if int(dense.btype[b]) == target_type and b not in seen:
                seen.add(b)
                stop.append(b)
    return stop


def build_pack(
    dense: DenseCrushMap,
    roots: list[int],
    target_type: int,
    consumer_map: dict[int, int],
) -> tuple[DescendPack, list[int]]:
    """Per-level tables for a descent from ``roots`` stopping at
    ``target_type``.  Returns (pack, stop_buckets) where stop_buckets
    lists the reachable target-type buckets in BFS order (the leaf
    pack's roots for chooseleaf, or the next choose's roots)."""
    levels = _bfs_levels(dense, roots, target_type, dense.max_depth + 2)
    maps = [{b: i for i, b in enumerate(lvl)} for lvl in levels]
    tables = []
    lane_nps = []
    for li, lvl in enumerate(levels):
        next_map = maps[li + 1] if li + 1 < len(levels) else {}
        lt, lane_np = _build_level_table(
            dense, lvl, next_map, consumer_map, target_type)
        tables.append(lt)
        lane_nps.append(lane_np)
    desc_tb = desc_meta = None
    if _whole_descent_on():
        packed = pallas_straw2.pack_descend_tables(lane_nps)
        if packed is not None:
            desc_tb, desc_meta = jnp.asarray(packed[0]), packed[1]
    if desc_tb is None and _want_lane_tables():
        # per-level kernels: mode 'level', or the fused table failed
        # its bounds — attach each level's lane table where it fits
        tables = [
            LevelTable(t.tb, t.nb, t.fanout,
                       None if ln is None else jnp.asarray(ln))
            for t, ln in zip(tables, lane_nps)
        ]
    return (DescendPack(tuple(tables), desc_tb, desc_meta),
            _stop_buckets(dense, roots, target_type))


def take_rows(table: LevelTable, lidx: jnp.ndarray) -> dict[str, jnp.ndarray]:
    """Per-lane bucket-row fetch via one-hot matmul; returns decoded
    field arrays, each [B, F] (size: [B]).

    Exact: bf16 one-hot x bf16 byte columns under f32 accumulation —
    each output element is a single 0/1 x [0,255] product.
    """
    F = table.fanout
    if table.nb == 1:
        acc = jnp.broadcast_to(
            table.tb[0].astype(jnp.float32)[None, :],
            (lidx.shape[0], table.tb.shape[1]),
        )
    else:
        onehot = (
            lidx[:, None] == jnp.arange(table.nb, dtype=I32)[None, :]
        ).astype(jnp.bfloat16)
        acc = jnp.dot(onehot, table.tb, preferred_element_type=jnp.float32)

    by = acc.astype(I32).astype(U32)  # every column is an exact byte

    def u32_from(off: int) -> jnp.ndarray:
        b = [by[:, (off + i) * F:(off + i + 1) * F] for i in range(4)]
        return b[0] | (b[1] << 8) | (b[2] << 16) | (b[3] << 24)

    ids_u = u32_from(_OFF_ID)
    ws = u32_from(_OFF_W)
    mag = u32_from(_OFF_MAG).astype(U64) | (
        u32_from(_OFF_MAG + 4).astype(U64) << np.uint64(32)
    )
    ct = by[:, _OFF_CTYPE * F:(_OFF_CTYPE + 1) * F].astype(I32)
    nlidx = (
        by[:, _OFF_NLIDX * F:(_OFF_NLIDX + 1) * F]
        | (by[:, (_OFF_NLIDX + 1) * F:(_OFF_NLIDX + 2) * F] << 8)
    ).astype(I32)
    size = (
        by[:, _SLOT_BYTES * F] | (by[:, _SLOT_BYTES * F + 1] << 8)
    ).astype(I32)
    return {
        "ids": ids_u, "weights": ws, "magic": mag,
        "ctype": ct, "nlidx": nlidx, "size": size,
    }


def _select_col(vals: jnp.ndarray, col: jnp.ndarray) -> jnp.ndarray:
    """vals[b, col[b]] without a gather: one-hot sum over the small
    fanout axis."""
    F = vals.shape[1]
    mask = col[:, None] == jnp.arange(F, dtype=I32)[None, :]
    # dtype= pins the accumulator: x64 mode would promote u32 sums to
    # u64, and a later bitcast would then split lanes.
    return jnp.sum(
        jnp.where(mask, vals, jnp.zeros_like(vals)), axis=1, dtype=vals.dtype
    )


def _negdraw(x2, ids, r2, w, magic):
    """straw2 negdraw dispatch: fused Pallas kernel on the chip (the
    jnp path's crush_ln LUT gathers cost ~10 ns/lane there — silicon
    profiling, round 3), plain jnp elsewhere.  Both are bit-exact
    (tests/test_pallas_straw2.py); CEPH_TPU_FUSED_STRAW2=0/1 forces a
    path (tests use 1 with interpret to cover the kernel on CPU)."""
    if _fused_straw2():
        return pallas_straw2.straw2_negdraw_fused(x2, ids, r2, w, magic)
    return hashes.straw2_negdraw_magic(x2, ids, r2, w, magic)


def _fused_straw2() -> bool:
    # default_backend() reports "tpu" through this machine's tunnel
    # plugin when properly attached (verified on silicon); "axon" only
    # appears when the env scrub is wrong, and then no device path
    # works anyway
    mode = os.environ.get("CEPH_TPU_FUSED_STRAW2", "auto")
    return mode == "1" or (mode == "auto" and jax.default_backend() == "tpu")


def _compact_window(B: int) -> int | None:
    """Straggler-window size for the compacted retry paths, or None
    when compaction should not engage (small batches, or the env gate
    off).  The floor means the window is B/16 for large batches but up
    to B/8 right at the threshold."""
    if B < (1 << 16) or not _retry_compact():
        return None
    return max(B // 16, 8192)


def _retry_compact() -> bool:
    """Whether big batches use the compacted-straggler retry path.

    Built-in default opt-in (CEPH_TPU_RETRY_COMPACT=1) until its
    compile time is proven bounded on the chip: the windowed
    gather/scatter roughly doubles the engine program and local
    chipless AOT went from ~45 s to >17 min for the kernel-mode 1M
    program — the same caution that kept the level kernels fenced in
    round 3.  bench/level_kernel_probe.py measures rate AND compile
    for the kernel x compaction grid in one chip session; the decision
    lands in ``bench/kernel_defaults.json`` (env overrides)."""
    env = os.environ.get("CEPH_TPU_RETRY_COMPACT")
    if env is not None:
        return env == "1"
    return str(_decided_defaults().get("CEPH_TPU_RETRY_COMPACT", "0")) == "1"


_DEFAULTS_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "bench", "kernel_defaults.json",
)
_defaults_cache: dict | None = None


def _decided_defaults() -> dict:
    """Data-decided engine defaults, written by
    ``bench/decide_defaults.py --write`` from an on-chip grid artifact
    (round-4 verdict item 8: defaults flip from measurements, with the
    artifact cited inside the file).  Env flags always override.  Absent
    or unreadable file -> conservative built-ins."""
    global _defaults_cache
    if _defaults_cache is None:
        try:
            import json as _json

            with open(_DEFAULTS_PATH) as f:
                loaded = _json.load(f)
            _defaults_cache = loaded if isinstance(loaded, dict) else {}
        except Exception:  # noqa: BLE001 — missing file is the normal case
            _defaults_cache = {}
    return _defaults_cache


_mode_override: str | None = None


class _force_kernel_mode:
    """Internal forcing lever (NOT an env flag): pins ``_kernel_mode``
    to a literal while the bit-exactness gate runs both sides of its
    comparison, and while the fused-pipeline differential tests do the
    same.  Re-entrancy guard for the gate: with the override set, the
    gate's own placements never consult the gate again."""

    def __init__(self, mode: str | None):
        self.mode = mode

    def __enter__(self):
        global _mode_override
        self.prev = _mode_override
        _mode_override = self.mode
        return self

    def __exit__(self, *exc):
        global _mode_override
        _mode_override = self.prev
        return False


def _decided_kernel_mode() -> str | None:
    """The defaults-file rung: accepts the legacy flat string form
    (applies to every platform) or the per-platform dict form written
    by ``decide_defaults.py`` (keyed by ``jax.default_backend()`` with
    an optional ``"default"`` fallback).  None when the file has no
    opinion for this platform."""
    decided = _decided_defaults().get("CEPH_TPU_LEVEL_KERNEL")
    if isinstance(decided, dict):
        decided = decided.get(jax.default_backend(), decided.get("default"))
    if decided is None:
        return None
    mode = str(decided)
    return mode if mode in ("0", "1", "level") else "0"


def _platform_default_mode() -> str:
    """Built-in rung of the ladder: the per-level Pallas kernels are
    the default batch-placement backend on TPU, *gated* on the
    golden-map bit-exactness probe (``crush/kernel_gate.py``) — the
    mode flips on only after the kernel path reproduces the scalar
    interp bit for bit in this process, and any gate failure falls
    back to the XLA one-hot-matmul path.  Off-TPU the matmul path
    stays the default (the kernels run there only in interpret mode,
    which is a correctness vehicle, not a fast path).  The fused
    whole-descent kernel (mode '1') stays opt-in everywhere: its
    Mosaic compile was never demonstrated bounded on silicon
    (ROUND5_NOTES.md)."""
    if jax.default_backend() != "tpu":
        return "0"
    from . import kernel_gate

    return "level" if kernel_gate.gate_passes() else "0"


def _kernel_mode() -> str:
    """'1' forces the Pallas level/descent kernels (interpret off-TPU),
    'level' forces the per-level kernels while keeping the fused
    whole-descent kernel OFF (its Mosaic program is ~levels x larger —
    the fallback lever if only the big kernel's on-chip compile is
    pathological), '0' forces the XLA matmul path.

    Resolution ladder: env flag (CEPH_TPU_LEVEL_KERNEL) ->
    ``bench/kernel_defaults.json`` (per-platform dict or legacy flat
    string, written only from measured probe data by
    ``decide_defaults.py --write``) -> built-in platform default
    ('level' on TPU gated on the golden-map bit-exactness probe,
    '0' elsewhere)."""
    if _mode_override is not None:
        return _mode_override
    env = os.environ.get("CEPH_TPU_LEVEL_KERNEL")
    if env is not None:
        return env
    decided = _decided_kernel_mode()
    if decided is not None:
        return decided
    return _platform_default_mode()


def kernel_mode_resolved() -> dict:
    """Resolved mode plus its provenance, for bench JSON lines: which
    rung of the ladder decided, and (when the gate was consulted) the
    gate's verdict detail."""
    if _mode_override is not None:
        return {"kernel_mode": _mode_override, "kernel_mode_source": "forced"}
    env = os.environ.get("CEPH_TPU_LEVEL_KERNEL")
    if env is not None:
        return {"kernel_mode": env, "kernel_mode_source": "env"}
    decided = _decided_kernel_mode()
    if decided is not None:
        return {"kernel_mode": decided, "kernel_mode_source": "defaults_file"}
    mode = _platform_default_mode()
    out = {"kernel_mode": mode, "kernel_mode_source": "builtin"}
    if jax.default_backend() == "tpu":
        from . import kernel_gate

        out["kernel_mode_source"] = "gate"
        out["kernel_gate"] = kernel_gate.gate_detail()
    return out


def _whole_descent_on() -> bool:
    """Whether descents may use the fused all-levels kernel (mode '1'
    only; mode 'level' stops at per-level kernels)."""
    return _kernel_mode() == "1"


def _want_lane_tables() -> bool:
    """Whether pack builds should spend host time + device memory on
    the level kernel's lane encoding at all (it is dead weight when the
    dispatch can never select the kernel).

    CEPH_TPU_FUSED_STRAW2=0 also disables the level kernel: it embeds
    the same Pallas straw2 math, so "force the jnp path" must win over
    the level dispatch or the escape hatch is a lie."""
    mode = _kernel_mode()
    fused_mode = os.environ.get("CEPH_TPU_FUSED_STRAW2", "auto")
    if fused_mode == "0":
        return False
    # strictly opt-in: ONLY the literal '1'/'level' enable kernels (a
    # legacy 'auto' value must not re-enable the unproven silicon
    # compile the default exists to fence off)
    return mode in ("1", "level")


def _use_level_kernel(table: LevelTable) -> bool:
    return table.lane_tb is not None and _want_lane_tables()


def descend(
    pack: DescendPack,
    x: jnp.ndarray,       # [B] u32
    lidx0: jnp.ndarray,   # [B] i32 level-0 local bucket index
    r: jnp.ndarray,       # [B] i32 per-lane replica seed
    target_type: int,
    empty_is_hard: bool,
    active: jnp.ndarray,  # [B] bool
    max_devices: int,
):
    """Batched hierarchy walk; mirrors ``interp._descend`` lane-for-lane.

    Returns (item, ok, hard, next_lidx), all [B]; ``next_lidx`` is the
    chosen bucket's local index in the consumer (leaf) pack, valid when
    the item is a target-type bucket.
    """
    B = x.shape[0]

    if pack.desc_tb is not None and _whole_descent_on():
        # whole descent in one Pallas call (all levels fused)
        return pallas_straw2.descend_fused(
            x, r.astype(U32), lidx0, active, pack.desc_tb, pack.desc_meta,
            target_type, empty_is_hard, max_devices)

    item = jnp.full((B,), ITEM_NONE, I32)
    ok = jnp.zeros((B,), bool)
    hard = jnp.zeros((B,), bool)
    done = ~active
    nlidx_out = jnp.zeros((B,), I32)
    lidx = lidx0

    for table in pack.tables:
        if _use_level_kernel(table):
            item_u, ctype, nlidx, size = pallas_straw2.level_choose(
                x, r.astype(U32), jnp.where(done, 0, lidx), table.lane_tb)
            chosen = lax.bitcast_convert_type(item_u, I32)
        else:
            row = take_rows(table, jnp.where(done, 0, lidx))
            nd = _negdraw(
                x[:, None], row["ids"], r[:, None].astype(U32),
                row["weights"], row["magic"],
            )  # [B, F] u64
            amin = jnp.argmin(nd, axis=1).astype(I32)  # first-index ties
            chosen = lax.bitcast_convert_type(
                _select_col(row["ids"], amin), I32)
            ctype = _select_col(row["ctype"], amin)
            nlidx = _select_col(row["nlidx"], amin)
            size = row["size"]

        empty = size == 0
        is_bucket = chosen < 0
        reached = (ctype == target_type) if target_type != 0 else ~is_bucket
        wrong_dev = (~is_bucket) & (~reached)
        bad_dev = (~is_bucket) & (chosen >= max_devices)
        bad_bucket = is_bucket & (ctype == _CTYPE_DANGLING)
        if empty_is_hard:
            hard_now = empty | wrong_dev | bad_dev | bad_bucket
            soft_now = jnp.zeros((B,), bool)
        else:
            hard_now = (~empty) & (wrong_dev | bad_dev | bad_bucket)
            soft_now = empty
        new_done = done | hard_now | soft_now | reached
        ok = jnp.where(done, ok, reached & ~hard_now & ~soft_now)
        hard = jnp.where(done, hard, hard_now)
        item = jnp.where(done, item, chosen)
        nlidx_out = jnp.where(done, nlidx_out, nlidx)
        lidx = jnp.where(new_done, lidx, nlidx)
        done = new_done

    # lanes not done after all levels: soft failure (depth exhausted)
    return item, ok, hard, nlidx_out


def _is_out(osd_weight, item, x):
    wmax = osd_weight.shape[0]
    oob = item >= wmax
    w = osd_weight[jnp.clip(item, 0, wmax - 1)]
    return oob | hashes.is_out(w, item.astype(U32), x)


def _collides(out: jnp.ndarray, outpos: jnp.ndarray, item: jnp.ndarray):
    """item[b] in out[b, :outpos[b]]; out has small static width."""
    cap = out.shape[1]
    pos_ok = jnp.arange(cap, dtype=I32)[None, :] < outpos[:, None]
    return jnp.any(pos_ok & (out == item[:, None]), axis=1)


def _append_rows(acc, acc_pos, vals, counts):
    """Per-lane append: acc[b, acc_pos[b] : acc_pos[b]+counts[b]] =
    vals[b, :counts[b]] (the reference's ``o + osize`` pointer offset),
    via a one-hot shift over the small static widths.  Positions beyond
    acc's width are dropped (masked writes)."""
    rm = acc.shape[1]
    c = vals.shape[1]
    idx = jnp.arange(rm, dtype=I32)[None, :]
    shift = idx - acc_pos[:, None]  # [B, rm]
    sel = shift[:, :, None] == jnp.arange(c, dtype=I32)[None, None, :]
    src = jnp.sum(
        jnp.where(sel, vals[:, None, :], 0), axis=2, dtype=vals.dtype
    )
    write = (shift >= 0) & (shift < counts[:, None])
    return jnp.where(write, src, acc), acc_pos + counts


def _leaf_firstn(
    leaf_pack, osd_weight, x, leaf_lidx, has_bucket, sub_r,
    recurse_tries: int, out2, outpos, stable: int, max_devices: int,
):
    """Batched ``interp._leaf_descend_firstn``. Returns (leaf, ok)."""
    B = x.shape[0]
    rep = jnp.zeros((B,), I32) if stable else outpos.astype(I32)

    def body(st):
        ftotal, settled, leaf_ok, leaf = st
        active = has_bucket & ~settled & (ftotal < recurse_tries)
        r = rep + sub_r + ftotal
        it, ok, hard, _ = descend(
            leaf_pack, x, leaf_lidx, r, 0, False, active, max_devices
        )
        collide = ok & _collides(out2, outpos, it)
        rejected = ok & (collide | _is_out(osd_weight, it, x))
        good = active & ok & ~rejected
        stop = active & hard  # hard leaf failure abandons the slot
        return (
            ftotal + 1,
            settled | good | stop,
            leaf_ok | good,
            jnp.where(good, it, leaf),
        )

    init = (
        jnp.asarray(0, I32), jnp.zeros((B,), bool),
        jnp.zeros((B,), bool), jnp.full((B,), ITEM_NONE, I32),
    )
    if recurse_tries == 1:
        st = body(init)
    else:
        st = lax.while_loop(
            lambda s: jnp.any(has_bucket & ~s[1]) & (s[0] < recurse_tries),
            body, init,
        )
    _, _, leaf_ok, leaf = st
    return leaf, leaf_ok


def _choose_firstn_batch(
    pack, leaf_pack, osd_weight, x, lidx0, start_active,
    numrep: int, target_type: int, cap: int, tries: int,
    recurse_tries: int, vary_r: int, stable: int, max_devices: int,
):
    """Batched ``interp._choose_firstn`` for one working-vector entry.

    Entry-local state, like the reference's per-entry
    ``choose_firstn(..., o + osize, /*outpos=*/0, ...)`` call: collision
    scope and the stable=0 leaf replica seed cover only this entry's
    segment.  Returns (out [B, cap], out2 [B, cap], outpos [B]).
    """
    B = x.shape[0]

    # Retry compaction (bench/PERF_MODEL.md suspect 4): the masked
    # whole-batch retry loop runs until the WORST lane settles — 4-6
    # full-batch rounds at 1M lanes (measured: cppref retry_stats,
    # max_ftotal 3 on config1 / 5 on skewed maps) although ~99.7 % of
    # lanes settle in round 1.  At scale, round 1 runs on the full
    # batch, then each later round gathers a window of up to B/16
    # stragglers (tracking per-lane ftotal, so a lane outside the
    # window simply waits with its retry seed unchanged — the body is
    # fully lane-local, making the gather semantics-preserving and the
    # window size a pure performance knob).
    CB = _compact_window(B)
    COMPACT = CB is not None and tries > 0  # tries<=0 places nothing

    def rep_step(carry, rep):
        # one replica slot; ``rep`` is a traced scalar so the whole
        # numrep loop is a lax.scan — the program is traced/compiled
        # once instead of numrep times (compile time and suite speed)
        out, out2, outpos = carry

        def one_round(xv, lidxv, rv, active, outv, out2v, outposv):
            """One retry round for any lane subset; returns
            (good, stop, item, leaf), all lane-local."""
            n = xv.shape[0]
            item, ok, hard, nlidx = descend(
                pack, xv, lidxv, rv, target_type, False, active,
                max_devices,
            )
            collide = ok & _collides(outv, outposv, item)
            reject = jnp.zeros((n,), bool)
            leaf = item
            if leaf_pack is not None:
                is_bucket = item < 0
                sub_r = (
                    (rv >> (vary_r - 1)) if vary_r
                    else jnp.zeros((n,), I32)
                )
                lf, lok = _leaf_firstn(
                    leaf_pack, osd_weight, xv, nlidx,
                    active & ok & ~collide & is_bucket,
                    sub_r, recurse_tries, out2v, outposv, stable,
                    max_devices,
                )
                leaf_ok = jnp.where(is_bucket, lok, True)
                leaf = jnp.where(is_bucket, lf, item)
                reject = reject | (ok & ~collide & ~leaf_ok)
            if target_type == 0:
                reject = reject | (
                    ok & ~collide & _is_out(osd_weight, item, xv)
                )
            good = active & ok & ~collide & ~reject
            stop = active & hard  # skip_rep: abandon this slot
            return good, stop, item, leaf

        if not COMPACT:
            def body(st):
                ftotal, settled, item_acc, leaf_acc, placed = st
                active = start_active & ~settled & (ftotal < tries)
                rB = jnp.broadcast_to(rep, (B,)) + ftotal
                good, stop, item, leaf = one_round(
                    x, lidx0, rB, active, out, out2, outpos
                )
                return (
                    ftotal + 1,
                    settled | good | stop,
                    jnp.where(good, item, item_acc),
                    jnp.where(good, leaf, leaf_acc),
                    placed | good,
                )

            init = (
                jnp.asarray(0, I32), jnp.zeros((B,), bool),
                jnp.full((B,), ITEM_NONE, I32),
                jnp.full((B,), ITEM_NONE, I32),
                jnp.zeros((B,), bool),
            )
            _, _, item, leaf, placed = lax.while_loop(
                lambda s: jnp.any(start_active & ~s[1]) & (s[0] < tries),
                body, init,
            )
        else:
            # round 1: the full batch, unrolled (every lane attempts)
            rB0 = jnp.broadcast_to(rep, (B,))
            good0, stop0, item0, leaf0 = one_round(
                x, lidx0, rB0, start_active, out, out2, outpos
            )
            settled = ~start_active | good0 | stop0
            item = jnp.where(good0, item0, ITEM_NONE)
            leaf = jnp.where(good0, leaf0, ITEM_NONE)
            placed = good0
            ftl = jnp.ones((B,), I32)  # unsettled lanes failed once

            def body_c(st):
                ftl, settled, item, leaf, placed = st
                # window of stragglers; filler index B: gathers clamp
                # (masked inactive), scatters drop — fillers can never
                # collide with a real lane's write
                idx = jnp.nonzero(~settled, size=CB, fill_value=B)[0]
                real = idx < B
                idxc = jnp.clip(idx, 0, B - 1)
                ftl_v = ftl[idxc]
                exhausted = ftl_v >= tries
                act = real & ~exhausted
                rv = jnp.broadcast_to(rep, (CB,)) + ftl_v
                good, stopv, it_r, lf_r = one_round(
                    x[idxc], lidx0[idxc], rv, act,
                    out[idxc], out2[idxc], outpos[idxc],
                )
                settled_v = good | stopv | exhausted
                failed = act & ~good & ~stopv
                item = item.at[idx].set(
                    jnp.where(good, it_r, item[idxc]), mode="drop")
                leaf = leaf.at[idx].set(
                    jnp.where(good, lf_r, leaf[idxc]), mode="drop")
                placed = placed.at[idx].set(
                    placed[idxc] | good, mode="drop")
                settled = settled.at[idx].set(settled_v, mode="drop")
                ftl = ftl.at[idx].set(
                    ftl_v + failed.astype(I32), mode="drop")
                return ftl, settled, item, leaf, placed

            _, _, item, leaf, placed = lax.while_loop(
                lambda s: jnp.any(~s[1]),
                body_c,
                (ftl, settled, item, leaf, placed),
            )

        place = placed & (outpos < cap)
        col = jnp.arange(cap, dtype=I32)[None, :] == outpos[:, None]
        out = jnp.where(col & place[:, None], item[:, None], out)
        if leaf_pack is not None:
            out2 = jnp.where(col & place[:, None], leaf[:, None], out2)
        outpos = outpos + place.astype(I32)
        return (out, out2, outpos), None

    init_carry = (
        jnp.full((B, cap), ITEM_NONE, I32),
        jnp.full((B, cap), ITEM_NONE, I32),
        jnp.zeros((B,), I32),
    )
    (out, out2, outpos), _ = lax.scan(
        rep_step, init_carry, jnp.arange(numrep, dtype=I32)
    )
    return out, out2, outpos


def _leaf_indep(
    leaf_pack, osd_weight, x, leaf_lidx, has_bucket, rep,
    numrep: int, parent_r, recurse_tries: int, max_devices: int,
):
    """Batched ``interp._indep_leaf``. Returns (leaf, ok)."""
    B = x.shape[0]
    repB = jnp.broadcast_to(jnp.asarray(rep, I32), (B,))

    def body(st):
        ft, settled, got, leaf = st
        active = has_bucket & ~settled
        r = repB + parent_r + numrep * ft
        it, ok, hard, _ = descend(
            leaf_pack, x, leaf_lidx, r, 0, True, active, max_devices
        )
        ok = ok & ~_is_out(osd_weight, it, x)
        newly = active & ok
        fail_now = active & hard  # permanent failure in the reference
        return (
            ft + 1,
            settled | newly | fail_now,
            got | newly,
            jnp.where(newly, it, leaf),
        )

    init = (
        jnp.asarray(0, I32), jnp.zeros((B,), bool),
        jnp.zeros((B,), bool), jnp.full((B,), ITEM_NONE, I32),
    )
    _, _, got, leaf = lax.while_loop(
        lambda s: jnp.any(has_bucket & ~s[1]) & (s[0] < recurse_tries),
        body, init,
    )
    return jnp.where(got, leaf, ITEM_NONE), got


def _choose_indep_batch(
    pack, leaf_pack, osd_weight, x, lidx0, start_active,
    out_size: int, numrep: int, target_type: int,
    tries: int, recurse_tries: int, max_devices: int,
):
    """Batched ``interp._choose_indep`` for one working entry.
    Returns (out [B, out_size], out2 [B, out_size])."""
    B = x.shape[0]
    out = jnp.where(
        start_active[:, None],
        jnp.full((B, out_size), ITEM_UNDEF, I32),
        jnp.full((B, out_size), ITEM_NONE, I32),
    )
    out2 = out

    def one_round(xv, lidxv, ftv, activev, outv, out2v):
        """One retry round (all slots) for any lane subset; ``ftv`` is
        the per-lane round counter (lane-local semantics: a lane's r
        sequence depends only on its own participation count)."""
        n = xv.shape[0]

        def slot_step(carry, rep):
            # rep is traced: the out_size slot loop is a lax.scan so
            # the descend program is traced/compiled once per round,
            # not out_size times (EC rules have out_size = k+m)
            outv, out2v = carry
            # rep is a traced scalar: column reads/writes lower to
            # dynamic_slice / dynamic_update_slice (not lane gathers)
            col = lambda a: lax.dynamic_index_in_dim(
                a, rep, axis=1, keepdims=False)
            setcol = lambda a, v: lax.dynamic_update_index_in_dim(
                a, v, rep, axis=1)
            undef = col(outv) == ITEM_UNDEF
            active = activev & undef
            rB = jnp.broadcast_to(rep, (n,)) + numrep * ftv
            item, ok, hard, nlidx = descend(
                pack, xv, lidxv, rB, target_type, True, active, max_devices
            )
            collide = ok & jnp.any(outv == item[:, None], axis=1)
            good = ok & ~collide
            leaf = item
            if leaf_pack is not None:
                is_bucket = item < 0
                lf, lok = _leaf_indep(
                    leaf_pack, osd_weight, xv, nlidx,
                    active & good & is_bucket,
                    rep, numrep, rB, recurse_tries, max_devices,
                )
                leaf_ok = jnp.where(is_bucket, lok, True)
                leaf = jnp.where(is_bucket, lf, item)
                good = good & leaf_ok
            if target_type == 0:
                good = good & ~_is_out(osd_weight, item, xv)
            write_item = active & good
            write_none = active & hard
            newv = jnp.where(
                write_item, item,
                jnp.where(write_none, ITEM_NONE, col(outv)),
            )
            outv = setcol(outv, newv)
            newl = jnp.where(
                write_item, leaf,
                jnp.where(write_none, ITEM_NONE, col(out2v)),
            )
            out2v = setcol(out2v, newl)
            return (outv, out2v), None

        (outv, out2v), _ = lax.scan(
            slot_step, (outv, out2v), jnp.arange(out_size, dtype=I32)
        )
        return outv, out2v

    CB = _compact_window(B)
    COMPACT = CB is not None and tries > 0  # tries<=0 places nothing
    if not COMPACT:
        def round_body(st):
            ftotal, out_, out2_ = st
            ftv = jnp.full((B,), ftotal, I32)
            out_, out2_ = one_round(x, lidx0, ftv, start_active, out_, out2_)
            return (ftotal + 1, out_, out2_)

        _, out, out2 = lax.while_loop(
            lambda s: jnp.any(s[1] == ITEM_UNDEF) & (s[0] < tries),
            round_body, (jnp.asarray(0, I32), out, out2),
        )
    else:
        # straggler compaction, as in _choose_firstn_batch: round 1 on
        # the full batch, later rounds gather a window of lanes that
        # still have UNDEF slots, tracking per-lane round counts
        out, out2 = one_round(
            x, lidx0, jnp.zeros((B,), I32), start_active, out, out2
        )
        ftl = jnp.ones((B,), I32)

        def body_c(st):
            ftl, out_, out2_ = st
            unsettled = jnp.any(out_ == ITEM_UNDEF, axis=1)
            idx = jnp.nonzero(unsettled, size=CB, fill_value=B)[0]
            real = idx < B
            idxc = jnp.clip(idx, 0, B - 1)
            ftl_v = ftl[idxc]
            act = real & (ftl_v < tries)
            o_v, o2_v = one_round(
                x[idxc], lidx0[idxc], ftl_v, act, out_[idxc], out2_[idxc]
            )
            # exhausted lanes resolve their remaining UNDEF to NONE so
            # the loop terminates (the full loop's post-pass does the
            # same conversion)
            exhausted = (~act & real)[:, None]
            o_v = jnp.where(exhausted & (o_v == ITEM_UNDEF), ITEM_NONE, o_v)
            o2_v = jnp.where(
                exhausted & (o2_v == ITEM_UNDEF), ITEM_NONE, o2_v)
            out_ = out_.at[idx].set(o_v, mode="drop")
            out2_ = out2_.at[idx].set(o2_v, mode="drop")
            ftl = ftl.at[idx].set(ftl_v + 1, mode="drop")
            return ftl, out_, out2_

        _, out, out2 = lax.while_loop(
            lambda s: jnp.any(s[1] == ITEM_UNDEF),
            body_c, (ftl, out, out2),
        )
    out = jnp.where(out == ITEM_UNDEF, ITEM_NONE, out)
    out2 = jnp.where(out2 == ITEM_UNDEF, ITEM_NONE, out2)
    return out, out2


def supports(dense: DenseCrushMap, rule: Rule) -> bool:
    """Whether this engine can run (dense, rule)."""
    if dense.algs_present() - {ALG_STRAW2}:
        return False
    tun = dense.tunables
    if tun.choose_local_tries or tun.choose_local_fallback_tries:
        return False
    # byte-packed field widths: type ids live in one byte (255 is the
    # dangling-child sentinel), level-local indices and sizes in two
    if dense.n_buckets and (
        int(dense.btype.max(initial=0)) >= _CTYPE_DANGLING
        or dense.n_buckets > 0xFFFF
        or dense.max_fanout > 0xFFFF
    ):
        return False
    take: int | None = None
    for s in rule.steps:
        if s.op == OP_TAKE:
            if s.arg1 >= 0:
                return False
            take = s.arg1
        elif s.op in (OP_SET_CHOOSE_LOCAL_TRIES,
                      OP_SET_CHOOSE_LOCAL_FALLBACK_TRIES):
            if s.arg1 > 0:
                return False
        elif s.op in _CHOOSE_OPS and take is None:
            return False
    return True


def compile_rule_batch(dense: DenseCrushMap, rule: Rule, result_max: int):
    """Build (packs, run): ``run(packs, osd_weight, xs)`` returns
    (results [B, result_max] i32, lens [B] i32).

    ``packs`` is a pytree passed as a traced argument, so maps sharing
    topology shape reuse the compiled program; the step program itself
    is specialized on the rule at trace time.
    """
    tun = dense.tunables
    if not supports(dense, rule):
        raise NotImplementedError(
            "batch engine: straw2-only maps, modern tunables, and bucket "
            "take targets (use interp.batch_do_rule for the general path)"
        )

    # ---- host-side plan + pack construction (one forward walk) ----
    plans: list[dict] = []
    choose_tries = tun.choose_total_tries
    chooseleaf_tries = 0
    vary_r = tun.chooseleaf_vary_r
    stable = tun.chooseleaf_stable
    roots: list[int] | None = None  # current descent roots (bucket idxs)
    for s in rule.steps:
        if s.op == OP_TAKE:
            roots = [-1 - s.arg1]
            plans.append({"op": "take", "bucket_id": s.arg1})
        elif s.op == OP_SET_CHOOSE_TRIES:
            if s.arg1 > 0:
                choose_tries = s.arg1
        elif s.op == OP_SET_CHOOSELEAF_TRIES:
            if s.arg1 > 0:
                chooseleaf_tries = s.arg1
        elif s.op == OP_SET_CHOOSELEAF_VARY_R:
            if s.arg1 >= 0:
                vary_r = s.arg1
        elif s.op == OP_SET_CHOOSELEAF_STABLE:
            if s.arg1 >= 0:
                stable = s.arg1
        elif s.op in _CHOOSE_OPS:
            firstn = s.op in (OP_CHOOSE_FIRSTN, OP_CHOOSELEAF_FIRSTN)
            recurse = s.op in (OP_CHOOSELEAF_FIRSTN, OP_CHOOSELEAF_INDEP)
            numrep = s.arg1
            if numrep <= 0:
                numrep += result_max
            p = {
                "op": "choose", "firstn": firstn, "recurse": recurse,
                "numrep": numrep, "type": s.arg2, "tries": choose_tries,
                "chooseleaf_tries": chooseleaf_tries,
                "vary_r": vary_r, "stable": stable,
                "pack": None, "leaf_pack": None, "root_ids": None,
            }
            if numrep > 0 and roots is not None:
                if recurse:
                    stop = _stop_buckets(dense, roots, s.arg2)
                    leaf_pack, _ = build_pack(dense, stop, 0, {})
                    leaf0_map = {b: i for i, b in enumerate(stop)}
                    pk, _ = build_pack(dense, roots, s.arg2, leaf0_map)
                    p["pack"], p["leaf_pack"] = pk, leaf_pack
                    p["root_ids"] = [-1 - b for b in roots]
                    roots = None  # leaves are devices; not chainable
                else:
                    pk, stop = build_pack(dense, roots, s.arg2, {})
                    p["pack"] = pk
                    p["root_ids"] = [-1 - b for b in roots]
                    roots = stop if s.arg2 != 0 else None
            plans.append(p)
        elif s.op == OP_EMIT:
            plans.append({"op": "emit"})

    pack_args = tuple(
        (p["pack"], p["leaf_pack"])
        for p in plans
        if p.get("op") == "choose" and p["pack"] is not None
    )
    max_devices = dense.max_devices

    def run(packs_, osd_weight, xs):
        x = jnp.asarray(xs, U32)
        B = x.shape[0]
        result = jnp.full((B, result_max), ITEM_NONE, I32)
        result_len = jnp.zeros((B,), I32)
        w_vals: jnp.ndarray | None = None  # [B, W] working vector
        w_size = jnp.zeros((B,), I32)
        take_pending: int | None = None
        choose_i = 0

        for p in plans:
            if p["op"] == "take":
                take_pending = p["bucket_id"]
                w_vals = None
            elif p["op"] == "choose":
                if p["pack"] is None:
                    continue
                pack, leaf_pack = packs_[choose_i]
                choose_i += 1
                root_ids = p["root_ids"]
                if take_pending is not None:
                    entries = 1
                    ent_lidx = [jnp.zeros((B,), I32)]
                    ent_active = [jnp.ones((B,), bool)]
                    take_pending = None
                else:
                    if w_vals is None:
                        continue
                    entries = w_vals.shape[1]
                    ent_lidx = []
                    ent_active = []
                    rid = jnp.asarray(root_ids, I32)  # [NB0]
                    for e in range(entries):
                        hit = w_vals[:, e][:, None] == rid[None, :]
                        ent_lidx.append(
                            jnp.sum(
                                jnp.where(
                                    hit,
                                    jnp.arange(len(root_ids), dtype=I32)[None, :],
                                    0,
                                ),
                                axis=1,
                            )
                        )
                        ent_active.append(
                            jnp.any(hit, axis=1)
                            & (jnp.asarray(e, I32) < w_size)
                        )
                # per-entry segments appended at per-lane offsets (the
                # reference's ``o + osize`` pointer bump; skipped
                # entries advance nothing, so later ones compact left)
                if entries > 1 and entries * p["numrep"] > result_max:
                    raise NotImplementedError(
                        "chained choose overflowing result_max trims "
                        "per-lane entry widths; not supported on the "
                        "batch engine"
                    )
                acc_w = min(entries * p["numrep"], result_max)
                acc = jnp.full((B, acc_w), ITEM_NONE, I32)
                acc_pos = jnp.zeros((B,), I32)
                if p["firstn"]:
                    cap = min(p["numrep"], result_max)
                    recurse_tries = (
                        p["chooseleaf_tries"]
                        if p["chooseleaf_tries"]
                        else (1 if tun.chooseleaf_descend_once else p["tries"])
                    )
                    for e in range(entries):
                        out, out2, outpos = _choose_firstn_batch(
                            pack,
                            leaf_pack if p["recurse"] else None,
                            osd_weight, x, ent_lidx[e], ent_active[e],
                            p["numrep"], p["type"], cap,
                            p["tries"], recurse_tries,
                            p["vary_r"], p["stable"], max_devices,
                        )
                        vals = out2 if p["recurse"] else out
                        acc, acc_pos = _append_rows(acc, acc_pos, vals, outpos)
                else:
                    os_e = min(p["numrep"], result_max)
                    recurse_tries = (
                        p["chooseleaf_tries"] if p["chooseleaf_tries"] else 1
                    )
                    for e in range(entries):
                        o, o2 = _choose_indep_batch(
                            pack,
                            leaf_pack if p["recurse"] else None,
                            osd_weight, x, ent_lidx[e], ent_active[e],
                            os_e, p["numrep"], p["type"],
                            p["tries"], recurse_tries, max_devices,
                        )
                        vals = o2 if p["recurse"] else o
                        width = jnp.where(ent_active[e], os_e, 0)
                        acc, acc_pos = _append_rows(acc, acc_pos, vals, width)
                w_vals = acc
                w_size = acc_pos
            elif p["op"] == "emit":
                if w_vals is None:
                    if take_pending is not None:
                        w_vals = jnp.full((B, 1), take_pending, I32)
                        w_size = jnp.ones((B,), I32)
                        take_pending = None
                    else:
                        continue
                result, _ = _append_rows(result, result_len, w_vals, w_size)
                result_len = jnp.minimum(result_len + w_size, result_max)
                w_vals = None
                w_size = jnp.zeros((B,), I32)

        return result, result_len

    # everything baked into run as a Python constant must be in the
    # compile-cache key: pack shapes alone don't distinguish two maps
    # whose BFS stop sets (root_ids) or take ids differ
    program_sig = tuple(
        (p["op"], p.get("bucket_id"))
        if p["op"] != "choose"
        else (
            "choose", p["firstn"], p["recurse"], p["numrep"], p["type"],
            p["tries"], p["chooseleaf_tries"], p["vary_r"], p["stable"],
            tuple(p["root_ids"]) if p["root_ids"] is not None else None,
            p["pack"].signature if p["pack"] is not None else None,
            p["leaf_pack"].signature if p["leaf_pack"] is not None else None,
        )
        for p in plans
    )
    return pack_args, run, program_sig


_FAST_CACHE: dict = {}
_PACK_CACHE: dict = {}


def _dispatch_sig() -> tuple:
    """Trace-time dispatch state that changes the compiled program —
    the RESOLVED booleans, not the raw env strings, so equivalent
    modes ('1' vs 'auto' on TPU) share one compiled executable."""
    return (_fused_straw2(), _want_lane_tables(), _whole_descent_on(),
            _retry_compact())


def fast_signature(dense: DenseCrushMap, rule: Rule, result_max: int) -> tuple:
    """Full compile-cache key for (dense, rule, result_max) — includes
    every map-derived constant baked into the traced program."""
    packs, run, program_sig = _packs_for(dense, rule, result_max)
    return (program_sig, dense.tunables, result_max, dense.max_devices,
            _dispatch_sig())


def _packs_for(dense: DenseCrushMap, rule: Rule, result_max: int):
    # lane tables are built conditionally on the dispatch mode, so the
    # pack cache must not serve a build made under a different mode
    pkey = (id(dense), rule_signature(rule), result_max,
            _want_lane_tables(), _whole_descent_on())
    hit = _PACK_CACHE.get(pkey)
    if hit is not None and hit[0] is dense:
        return hit[1], hit[2], hit[3]
    packs, run, program_sig = compile_rule_batch(dense, rule, result_max)
    _memo_put(_PACK_CACHE, pkey, (dense, packs, run, program_sig))
    return packs, run, program_sig


def fast_runner(dense: DenseCrushMap, rule: Rule, result_max: int):
    """Cached (packs, jitted run) for ``dense``/``rule``.

    The compiled program is memoized by the full program signature
    (rule structure, tunables, pack shapes, AND the map-derived
    constants baked into the trace — take ids, chained-choose root
    ids); the packs themselves are memoized per dense-map object so
    repeated calls with the same map skip the host-side rebuild.
    """
    packs, run, program_sig = _packs_for(dense, rule, result_max)
    key = (program_sig, dense.tunables, result_max, dense.max_devices,
           _dispatch_sig())
    fn = _FAST_CACHE.get(key)
    if fn is None:
        fn = jax.jit(run)
        _memo_put(_FAST_CACHE, key, fn)
    return packs, fn


def batch_do_rule_fast(
    dense: DenseCrushMap, rule: Rule, xs, osd_weight, result_max: int
):
    """Level-synchronous batched rule execution — drop-in replacement
    for ``interp.batch_do_rule`` when ``supports(dense, rule)``.

    Returns (results [n, result_max] int32, lens [n] int32).
    """
    packs, fn = fast_runner(dense, rule, result_max)
    return fn(packs, jnp.asarray(osd_weight, U32), jnp.asarray(xs, U32))
