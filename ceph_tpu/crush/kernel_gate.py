"""Bit-exactness gate for the Pallas level-kernel straw2 default.

The level kernels (``core/pallas_straw2.py``) have been bit-exact in
tests since round 3, but they only become the *default* batch-placement
backend on a platform after this gate re-proves that equivalence in the
running process: the same golden map shapes the non-regression archive
pins (``testing/nonregression.crush_cases``) are placed once through
the scalar ``vmap`` interpreter (:mod:`ceph_tpu.crush.interp` — itself
differentially tested against the in-repo C++ reference) and once
through the level-kernel path, and the results must match bit for bit.

Any divergence — or any failure to build/compile/run the kernels at
all (no Mosaic support, interpret-mode breakage, out-of-bounds level
shapes) — resolves the gate to False and ``interp_batch._kernel_mode``
falls back to the XLA one-hot-matmul path.  The gate therefore encodes
the ladder's safety property: the default can *flip on* only on a
platform where the kernel path just demonstrated reference semantics,
and flips itself back off on any platform where it cannot.

The verdict is memoized per backend for the process lifetime; benches
surface it through ``interp_batch.kernel_mode_resolved()``.
"""

from __future__ import annotations

import numpy as np

import jax

#: seeds per golden map — enough to exercise retries/collisions on the
#: weighted and hierarchical shapes, small enough that the one-time
#: probe costs a handful of tiny compiles
GATE_SEEDS = 512

_GATE_CACHE: dict[str, bool] = {}
_GATE_DETAIL: dict[str, str] = {}


def golden_maps() -> dict:
    """The archive trio: flat, weighted-flat (uneven straw2 draws), and
    rack/host/osd (chooseleaf descent) — same builders the golden
    archive digests were generated from."""
    from ceph_tpu.models.clusters import build_flat, build_hierarchy

    weighted = build_flat(7)
    root = weighted.bucket_by_name("default")
    for i, osd in enumerate(root.items):
        weighted.adjust_item_weight(root.id, osd, 0x8000 + i * 0x4000)
    return {
        "flat_16": build_flat(16),
        "flat_7_weighted": weighted,
        "rack_host_osd": build_hierarchy([("rack", 2), ("host", 4)], 4),
    }


def check_bit_exact(n_seeds: int = GATE_SEEDS, mode: str = "level") -> None:
    """Raise unless the kernel path for ``mode`` ('level' per-level
    kernels, '1' fused whole-descent) reproduces the scalar interp bit
    for bit on every golden map (results AND lens)."""
    from . import interp, interp_batch

    runs = []
    for name, m in golden_maps().items():
        rule = m.rule_by_name("replicated_rule")
        dense = m.to_dense()
        xs = np.arange(n_seeds, dtype=np.uint32)
        w = np.full(dense.max_devices, 0x10000, np.uint32)
        smap = interp.StaticCrushMap(dense)
        ref = interp.batch_do_rule(smap, rule, xs, w, 3)
        with interp_batch._force_kernel_mode(mode):
            got = interp_batch.batch_do_rule_fast(dense, rule, xs, w, 3)
        runs.append((name, ref, got))
    # device sync once, after every program has been dispatched
    for name, (ref_res, ref_len), (got_res, got_len) in runs:
        if not (
            np.array_equal(np.asarray(ref_res), np.asarray(got_res))
            and np.array_equal(np.asarray(ref_len), np.asarray(got_len))
        ):
            raise AssertionError(
                f"kernel mode {mode!r} diverges from scalar interp on {name}"
            )


def gate_passes() -> bool:
    """Memoized per-backend verdict: may the level kernels be the
    built-in default here?  Never raises."""
    backend = jax.default_backend()
    hit = _GATE_CACHE.get(backend)
    if hit is None:
        try:
            check_bit_exact()
            hit, detail = True, "bit-exact on golden maps"
        except Exception as e:  # noqa: BLE001 — any failure means "fall back"
            hit, detail = False, f"{type(e).__name__}: {e}"
        _GATE_CACHE[backend] = hit
        _GATE_DETAIL[backend] = detail
    return hit


def gate_detail() -> str:
    """Human-readable verdict provenance for bench JSON lines."""
    backend = jax.default_backend()
    if backend not in _GATE_CACHE:
        return "not probed"
    return _GATE_DETAIL[backend]
