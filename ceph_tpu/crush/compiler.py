"""Text crushmap compiler/decompiler.

Parity with the reference's ``src/crush/CrushCompiler.{h,cc}`` (the
boost::spirit grammar in ``src/crush/grammar.h``): the classic text
format with ``tunable``/``device``/``type``/bucket/``rule`` sections
compiles to a :class:`~ceph_tpu.crush.map.CrushMap` and back.  Weights
are decimal in text (1.000) and 16.16 fixed point internally.
"""

from __future__ import annotations

from .map import (
    ALG_IDS,
    ALG_NAMES,
    OP_CHOOSE_FIRSTN,
    OP_CHOOSE_INDEP,
    OP_CHOOSELEAF_FIRSTN,
    OP_CHOOSELEAF_INDEP,
    OP_EMIT,
    OP_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    OP_SET_CHOOSE_LOCAL_TRIES,
    OP_SET_CHOOSE_TRIES,
    OP_SET_CHOOSELEAF_STABLE,
    OP_SET_CHOOSELEAF_TRIES,
    OP_SET_CHOOSELEAF_VARY_R,
    OP_TAKE,
    CrushMap,
    Rule,
    Step,
    Tunables,
)

TUNABLE_FIELDS = {
    "choose_total_tries": "choose_total_tries",
    "choose_local_tries": "choose_local_tries",
    "choose_local_fallback_tries": "choose_local_fallback_tries",
    "chooseleaf_descend_once": "chooseleaf_descend_once",
    "chooseleaf_vary_r": "chooseleaf_vary_r",
    "chooseleaf_stable": "chooseleaf_stable",
}

SET_OPS = {
    OP_SET_CHOOSE_TRIES: "set_choose_tries",
    OP_SET_CHOOSELEAF_TRIES: "set_chooseleaf_tries",
    OP_SET_CHOOSE_LOCAL_TRIES: "set_choose_local_tries",
    OP_SET_CHOOSE_LOCAL_FALLBACK_TRIES: "set_choose_local_fallback_tries",
    OP_SET_CHOOSELEAF_VARY_R: "set_chooseleaf_vary_r",
    OP_SET_CHOOSELEAF_STABLE: "set_chooseleaf_stable",
}
SET_OPS_BY_NAME = {v: k for k, v in SET_OPS.items()}


class CompileError(ValueError):
    pass


def compile_crushmap(text: str) -> CrushMap:
    """Text -> CrushMap (reference ``CrushCompiler::compile``)."""
    tun: dict[str, int] = {}
    m = CrushMap()
    lines: list[list[str]] = []
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].strip()
        if line:
            lines.append(line.split())

    i = 0
    n = len(lines)
    while i < n:
        tok = lines[i]
        if tok[0] == "tunable":
            if tok[1] not in TUNABLE_FIELDS:
                raise CompileError(f"unknown tunable {tok[1]}")
            tun[TUNABLE_FIELDS[tok[1]]] = int(tok[2])
            i += 1
        elif tok[0] == "device":
            osd = int(tok[1])
            name = tok[2]
            dclass = None
            if len(tok) >= 5 and tok[3] == "class":
                dclass = tok[4]
            m.add_device(osd, name, dclass)
            i += 1
        elif tok[0] == "type":
            m.add_type(int(tok[1]), tok[2])
            i += 1
        elif tok[0] == "rule":
            name = tok[1]
            if tok[-1] != "{":
                raise CompileError(f"rule {name}: expected '{{'")
            i += 1
            rid = None
            kind = "replicated"
            steps: list[Step] = []
            while i < n and lines[i][0] != "}":
                t = lines[i]
                if t[0] in ("id", "ruleset"):
                    rid = int(t[1])
                elif t[0] == "type":
                    kind = t[1]
                elif t[0] in ("min_size", "max_size"):
                    pass  # legacy, ignored (as modern reference does)
                elif t[0] == "step":
                    steps.append(_parse_step(m, t[1:]))
                else:
                    raise CompileError(f"rule {name}: bad line {t}")
                i += 1
            if i >= n:
                raise CompileError(f"rule {name}: unterminated block")
            i += 1  # closing }
            m.add_rule(name, steps, kind=kind, rule_id=rid)
        elif len(tok) >= 2 and tok[-1] == "{":
            # bucket: "<typename> <name> {"
            type_name = tok[0]
            bname = tok[1]
            i += 1
            bid = None
            alg = None
            items: list[tuple[str, int]] = []
            while i < n and lines[i][0] != "}":
                t = lines[i]
                if t[0] == "id":
                    bid = int(t[1])
                elif t[0] == "alg":
                    if t[1] not in ALG_IDS:
                        raise CompileError(f"bucket {bname}: bad alg {t[1]}")
                    alg = ALG_IDS[t[1]]
                elif t[0] == "hash":
                    if int(t[1]) != 0:
                        raise CompileError("only hash 0 (rjenkins1) exists")
                elif t[0] == "item":
                    iname = t[1]
                    weight = 0x10000
                    for j in range(2, len(t) - 1):
                        if t[j] == "weight":
                            weight = int(round(float(t[j + 1]) * 0x10000))
                    items.append((iname, weight))
                elif t[0] == "weight":
                    pass  # bucket combined weight: derived
                else:
                    raise CompileError(f"bucket {bname}: bad line {t}")
                i += 1
            if i >= n:
                raise CompileError(f"bucket {bname}: unterminated block")
            i += 1
            b = m.add_bucket(bname, type_name, alg=alg or 5, bucket_id=bid)
            for iname, w in items:
                m.insert_item(b.id, _item_id(m, iname), w)
        else:
            raise CompileError(f"unparsed line: {' '.join(tok)}")
    if tun:
        m.set_tunables(Tunables(**{**Tunables().__dict__, **tun}))
    return m


def _item_id(m: CrushMap, name: str) -> int:
    for osd, dname in m.device_names.items():
        if dname == name:
            return osd
    if name.startswith("osd."):
        return int(name.split(".", 1)[1])
    return m.bucket_by_name(name).id


def _parse_step(m: CrushMap, t: list[str]) -> Step:
    if t[0] == "take":
        if len(t) >= 4 and t[2] == "class":
            root = m.bucket_by_name(t[1]).id
            return Step(OP_TAKE, m.class_shadow_root(root, t[3]))
        return Step(OP_TAKE, m.bucket_by_name(t[1]).id)
    if t[0] == "emit":
        return Step(OP_EMIT)
    if t[0] in ("choose", "chooseleaf"):
        mode = t[1]  # firstn | indep
        num = int(t[2])
        if t[3] != "type":
            raise CompileError(f"step {t}: expected 'type'")
        type_id = m.type_id(t[4])
        op = {
            ("choose", "firstn"): OP_CHOOSE_FIRSTN,
            ("choose", "indep"): OP_CHOOSE_INDEP,
            ("chooseleaf", "firstn"): OP_CHOOSELEAF_FIRSTN,
            ("chooseleaf", "indep"): OP_CHOOSELEAF_INDEP,
        }[(t[0], mode)]
        return Step(op, num, type_id)
    if t[0] in SET_OPS_BY_NAME:
        return Step(SET_OPS_BY_NAME[t[0]], int(t[1]))
    raise CompileError(f"unknown step {t}")


def decompile_crushmap(m: CrushMap) -> str:
    """CrushMap -> text (reference ``CrushCompiler::decompile``)."""
    out: list[str] = ["# begin crush map"]
    t = m.tunables
    for text_name, field in TUNABLE_FIELDS.items():
        out.append(f"tunable {text_name} {getattr(t, field)}")
    out.append("")
    out.append("# devices")
    for osd in sorted(m.device_names):
        line = f"device {osd} {m.device_names[osd]}"
        if osd in m.device_classes:
            line += f" class {m.device_classes[osd]}"
        out.append(line)
    out.append("")
    out.append("# types")
    for tid in sorted(m.types):
        out.append(f"type {tid} {m.types[tid]}")
    out.append("")
    out.append("# buckets")
    # children before parents (the reference emits leaves first)
    emitted: set[int] = set()

    def emit_bucket(bid: int) -> None:
        if bid in emitted or m.shadow_origin(bid) is not None:
            return  # shadow trees are derived, not authored
        b = m.buckets[bid]
        for item in b.items:
            if item < 0:
                emit_bucket(item)
        emitted.add(bid)
        out.append(f"{m.types[b.type_id]} {b.name} {{")
        out.append(f"\tid {b.id}")
        out.append(f"\talg {ALG_NAMES[b.alg]}")
        out.append("\thash 0\t# rjenkins1")
        for item, w in zip(b.items, b.item_weights):
            # %.5f like the reference's decompiler: 5 decimals resolve
            # every 16.16 step (error x 0x10000 < 0.5, so the parse's
            # round() recovers the exact fixed-point weight; 3 decimals
            # lost up to ~33/65536 per item — found by the round-trip
            # placement fuzz)
            out.append(f"\titem {m.item_name(item)} weight {w / 0x10000:.5f}")
        out.append("}")

    for bid in sorted(m.buckets, reverse=True):
        emit_bucket(bid)
    out.append("")
    out.append("# rules")
    for r in sorted(m.rules.values(), key=lambda r: r.id):
        out.append(f"rule {r.name} {{")
        out.append(f"\tid {r.id}")
        out.append(f"\ttype {r.kind}")
        for s in r.steps:
            out.append("\tstep " + _step_text(m, s))
        out.append("}")
    out.append("")
    out.append("# end crush map")
    return "\n".join(out) + "\n"


def _step_text(m: CrushMap, s: Step) -> str:
    if s.op == OP_TAKE:
        origin = m.shadow_origin(s.arg1)
        if origin is not None:
            orig_id, cls = origin
            return f"take {m.buckets[orig_id].name} class {cls}"
        return f"take {m.buckets[s.arg1].name}"
    if s.op == OP_EMIT:
        return "emit"
    names = {
        OP_CHOOSE_FIRSTN: "choose firstn",
        OP_CHOOSE_INDEP: "choose indep",
        OP_CHOOSELEAF_FIRSTN: "chooseleaf firstn",
        OP_CHOOSELEAF_INDEP: "chooseleaf indep",
    }
    if s.op in names:
        return f"{names[s.op]} {s.arg1} type {m.types[s.arg2]}"
    if s.op in SET_OPS:
        return f"{SET_OPS[s.op]} {s.arg1}"
    raise CompileError(f"cannot decompile step op {s.op}")
