"""Vectorized CRUSH rule interpreter (the TPU hot path).

Re-implements the placement semantics of the reference's rule engine
(upstream ``src/crush/mapper.c :: crush_do_rule / crush_choose_firstn /
crush_choose_indep / crush_bucket_choose / bucket_perm_choose``) as a
batch program: one ``vmap`` over object seeds ``x`` replaces the
reference's serial per-object loop (its own batch answer is a CPU
threadpool, ``src/osd/OSDMapMapping.h :: ParallelPGMapper``).

Design notes (TPU-first, not a translation):

- **Trace-time specialization.**  Rule steps, replica counts, tunables
  and map *shape* (bucket count, fanout, depth) are Python-static: every
  rule compiles to a straight-line XLA program of bounded loops.  SET_*
  steps fold into static tunables at trace time.
- **Bounded masked loops instead of goto ladders.**  Each replica slot
  runs a ``lax.while_loop`` over full-descent retries; the hierarchy
  descent itself is a masked ``fori_loop`` over the map's static max
  depth.  Under ``vmap`` all lanes step together until the slowest lane
  finishes -- the price of SIMD divergence, paid for with ~10^3x ALU
  width versus one CPU core.
- **Hard-fail vs soft-fail retries.**  The reference distinguishes
  ``skip_rep`` (malformed item / wrong-type device: abandon the replica
  slot) from ``reject`` (collision/out/empty: retry with r' = r+ftotal).
  The descent returns both flags so the ladders match exactly.
- **straw2 as unsigned argmin.**  See ceph_tpu.core.hashes: the signed
  64-bit draw division becomes an unsigned negdraw; argmin's first-index
  tie rule matches the reference's strict-greater scan.
- **Whole-bucket vector choose.**  A straw2 choose hashes all
  ``max_fanout`` slots of a bucket row at once (padded weights are 0 =>
  never win), turning the reference's per-item scalar loop into a lane-
  parallel reduction.

The result for each x is a fixed ``[result_max]`` int32 vector padded
with ITEM_NONE -- FIRSTN results are compacted to the front, INDEP
results positional with NONE holes, exactly like the reference.

Current scope limits (explicit, enforced with clear errors): rules must
be single-TAKE chains with one choose step ("take; [set_*;] choose*;
emit"), covering the standard replicated/EC rules; legacy local-retry
tunables (argonaut profile) are CPU-reference-only.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ceph_tpu.core import hashes
from .map import (
    ALG_STRAW2,
    ALG_UNIFORM,
    ITEM_NONE,
    DenseCrushMap,
    OP_CHOOSE_FIRSTN,
    OP_CHOOSE_INDEP,
    OP_CHOOSELEAF_FIRSTN,
    OP_CHOOSELEAF_INDEP,
    OP_EMIT,
    OP_SET_CHOOSE_TRIES,
    OP_SET_CHOOSELEAF_TRIES,
    OP_SET_CHOOSE_LOCAL_TRIES,
    OP_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    OP_SET_CHOOSELEAF_VARY_R,
    OP_SET_CHOOSELEAF_STABLE,
    OP_TAKE,
    Rule,
)

ITEM_UNDEF = 0x7FFFFFFE

I32 = jnp.int32
U32 = jnp.uint32

FALSE = lambda: jnp.asarray(False)  # noqa: E731


class StaticCrushMap:
    """Device-resident dense map + static shape/tunable info (pytree)."""

    def __init__(self, dense: DenseCrushMap):
        self.n_buckets = dense.n_buckets
        self.max_fanout = dense.max_fanout
        self.max_devices = dense.max_devices
        self.max_depth = max(dense.max_depth, 1)
        self.tunables = dense.tunables
        self.algs = frozenset(dense.algs_present())
        unsupported = self.algs - {ALG_UNIFORM, ALG_STRAW2}
        if unsupported:
            raise NotImplementedError(
                f"bucket algs {sorted(unsupported)} (list/tree/straw1) are "
                "legacy and not supported on the TPU path; use straw2/uniform"
            )
        self.alg = jnp.asarray(dense.alg, I32)
        self.btype = jnp.asarray(dense.btype, I32)
        self.size = jnp.asarray(dense.size, I32)
        self.items = jnp.asarray(dense.items, I32)
        self.weights = jnp.asarray(dense.weights, U32)
        # hoisted straw2 reciprocals: device never divides in the hot loop
        self.magic = jnp.asarray(hashes.magic_reciprocal(dense.weights))

    def tree_flatten(self):
        arrays = (
            self.alg,
            self.btype,
            self.size,
            self.items,
            self.weights,
            self.magic,
        )
        static = (
            self.n_buckets,
            self.max_fanout,
            self.max_devices,
            self.max_depth,
            self.tunables,
            self.algs,
        )
        return arrays, static

    @classmethod
    def tree_unflatten(cls, static, arrays):
        obj = cls.__new__(cls)
        (
            obj.n_buckets,
            obj.max_fanout,
            obj.max_devices,
            obj.max_depth,
            obj.tunables,
            obj.algs,
        ) = static
        (
            obj.alg,
            obj.btype,
            obj.size,
            obj.items,
            obj.weights,
            obj.magic,
        ) = arrays
        return obj


jax.tree_util.register_pytree_node(
    StaticCrushMap,
    lambda m: m.tree_flatten(),
    StaticCrushMap.tree_unflatten,
)


def _straw2_choose(smap: StaticCrushMap, bidx, x, r):
    """items[argmin negdraw] for bucket row bidx; padded weights never win."""
    ids = smap.items[bidx]  # [F] i32 (original ids; hashed as u32)
    ws = smap.weights[bidx]  # [F] u32
    valid = jnp.arange(smap.max_fanout) < smap.size[bidx]
    ws = jnp.where(valid, ws, np.uint32(0))
    nd = hashes.straw2_negdraw_magic(
        jnp.full((smap.max_fanout,), x, U32),
        ids.astype(U32),
        jnp.full((smap.max_fanout,), r, U32).astype(U32),
        ws,
        smap.magic[bidx],
    )
    # All-zero weights: argmin picks index 0 = first real item, matching
    # the reference's scan initialization (size > 0 ensured by callers).
    return smap.items[bidx, jnp.argmin(nd)]


def _perm_choose(smap: StaticCrushMap, bidx, x, r):
    """Uniform bucket: seeded Fisher-Yates permutation, stateless."""
    size = smap.size[bidx]
    bucket_id = (-1 - bidx).astype(I32)
    size_u = jnp.maximum(size, 1).astype(U32)
    pr = (r.astype(U32) % size_u).astype(I32)
    F = smap.max_fanout

    def body(p, perm):
        active = (p <= pr) & (p < size - 1)
        i = (
            hashes.crush_hash32_3(
                x, bucket_id.astype(U32), jnp.asarray(p, I32).astype(U32)
            )
            % jnp.maximum(size - p, 1).astype(U32)
        ).astype(I32)
        do_swap = active & (i > 0)
        i = jnp.where(do_swap, i, 0)
        pi = perm[p + i]
        pp = perm[p]
        perm = perm.at[p + i].set(jnp.where(do_swap, pp, pi))
        perm = perm.at[p].set(jnp.where(do_swap, pi, pp))
        return perm

    # i32-pinned bounds: raw Python ints trace the counter as i64
    # under the package-wide x64 mode (jaxlint J002)
    perm = lax.fori_loop(
        jnp.int32(0), jnp.int32(F), body, jnp.arange(F, dtype=I32)
    )
    return smap.items[bidx, perm[pr]]


def _bucket_choose(smap: StaticCrushMap, bidx, x, r):
    if smap.algs <= {ALG_STRAW2}:
        return _straw2_choose(smap, bidx, x, r)
    if smap.algs <= {ALG_UNIFORM}:
        return _perm_choose(smap, bidx, x, r)
    return lax.cond(
        smap.alg[bidx] == ALG_UNIFORM,
        lambda: _perm_choose(smap, bidx, x, r),
        lambda: _straw2_choose(smap, bidx, x, r),
    )


def _is_out(osd_weight, item, x):
    wmax = osd_weight.shape[0]
    oob = item >= wmax
    w = osd_weight[jnp.clip(item, 0, wmax - 1)]
    return oob | hashes.is_out(w, item.astype(U32), x)


def _descend(
    smap: StaticCrushMap,
    x,
    start_bucket_idx,
    target_type: int,
    level_r_fn,
    empty_is_hard: bool = False,
):
    """Walk down from a bucket until an item of target_type is chosen.

    ``level_r_fn(bidx)`` gives the r used at each level (constant for
    FIRSTN; alg-dependent for INDEP spacing).

    Returns (item, ok, hard, r_final):
      ok   -- an item of target_type was chosen
      hard -- unrecoverable failure (bad device id, device met while a
              bucket type was wanted, malformed bucket id): the caller
              must abandon the slot (reference's skip_rep / NONE-break)
      neither -- soft failure (empty bucket / depth exhausted): retry.
      r_final -- the r used at the level where the walk stopped (the
              chooseleaf-indep recursion's parent_r).

    ``empty_is_hard``: INDEP marks a slot permanently NONE on an empty
    bucket, while FIRSTN retries the descent (the reference's reject
    ladder) -- the caller picks the behavior.
    """

    def body(_, st):
        bidx, item, done, ok, hard, r_out = st
        r = level_r_fn(bidx)
        empty = smap.size[bidx] == 0
        chosen = _bucket_choose(smap, bidx, x, r)
        bad_dev = chosen >= smap.max_devices
        is_bucket = chosen < 0
        sub_idx = jnp.clip(-1 - chosen, 0, smap.n_buckets - 1)
        bad_bucket = is_bucket & ((-1 - chosen) >= smap.n_buckets)
        itemtype = jnp.where(is_bucket, smap.btype[sub_idx], 0)
        reached = itemtype == target_type
        # wrong type and not descendable => hard fail
        wrong_dev = (~is_bucket) & (~reached)
        if empty_is_hard:
            hard_now = empty | bad_dev | bad_bucket | wrong_dev
            soft_now = FALSE()
        else:
            hard_now = (~empty) & (bad_dev | bad_bucket | wrong_dev)
            soft_now = empty
        new_done = done | hard_now | soft_now | reached
        new_ok = jnp.where(done, ok, reached & ~hard_now & ~soft_now)
        new_hard = jnp.where(done, hard, hard_now)
        new_item = jnp.where(done, item, chosen)
        new_r = jnp.where(done, r_out, r)
        descend = (~new_done) & is_bucket
        new_bidx = jnp.where(descend, sub_idx, bidx)
        return (new_bidx, new_item, new_done, new_ok, new_hard, new_r)

    init = (
        start_bucket_idx.astype(I32),
        jnp.asarray(ITEM_NONE, I32),
        FALSE(),
        FALSE(),
        FALSE(),
        jnp.asarray(0, I32),
    )
    bidx, item, done, ok, hard, r_out = lax.fori_loop(
        jnp.int32(0), jnp.int32(smap.max_depth + 1), body, init
    )
    # depth exhausted without reaching target: soft failure
    return item, ok, hard, r_out


def _leaf_descend_firstn(
    smap: StaticCrushMap,
    osd_weight,
    x,
    bucket_item,
    sub_r,
    recurse_tries: int,
    out2,
    outpos,
    stable: int,
):
    """chooseleaf-firstn recursion: one replica slot, target type 0.

    The reference's recursive crush_choose_firstn call uses
    numrep = stable ? 1 : outpos+1, which always runs exactly one
    iteration at rep = stable ? 0 : outpos.  Collisions are checked
    against previously chosen leaves out2[0:outpos].
    Returns (leaf, ok).
    """
    rep = jnp.asarray(0, I32) if stable else outpos.astype(I32)
    bidx = jnp.clip(-1 - bucket_item, 0, smap.n_buckets - 1)
    npos = out2.shape[0]

    def cond(st):
        ftotal, done, hard_stop, _ = st
        return (~done) & (~hard_stop) & (ftotal < recurse_tries)

    def body(st):
        ftotal, _, _, leaf = st
        r = rep + sub_r + ftotal
        item, ok, hard, _ = _descend(smap, x, bidx, 0, lambda _b: r)
        collide = ok & jnp.any((jnp.arange(npos) < outpos) & (out2 == item))
        rejected = ok & (collide | _is_out(osd_weight, item, x))
        good = ok & ~rejected
        return (ftotal + 1, good, hard, jnp.where(good, item, leaf))

    _, ok, _, leaf = lax.while_loop(
        cond,
        body,
        (jnp.asarray(0, I32), FALSE(), FALSE(), jnp.asarray(ITEM_NONE, I32)),
    )
    return leaf, ok


def _choose_firstn(
    smap: StaticCrushMap,
    osd_weight,
    x,
    take_bucket_idx,
    numrep: int,
    target_type: int,
    out_size: int,
    tries: int,
    recurse_tries: int,
    recurse_to_leaf: bool,
    vary_r: int,
    stable: int,
):
    """FIRSTN selection below one take bucket.

    Iterates all numrep replica slots (the reference's
    ``rep < numrep && count > 0`` loop) but places at most ``out_size``
    results -- slots whose retries are exhausted are skipped while later
    slots can still fill the quota.

    Returns (out [out_size], out2 [out_size], n_placed).  out is
    compacted (chosen items first, ITEM_NONE padding); out2 holds the
    leaves when recurse_to_leaf.
    """
    cap = out_size
    out = jnp.full((cap,), ITEM_NONE, I32)
    out2 = jnp.full((cap,), ITEM_NONE, I32)
    outpos = jnp.asarray(0, I32)

    # Speculative retry blocks: the reference's retry ladder for one
    # replica slot visits r = rep, rep+1, rep+2, ... (ftotal increments
    # by one per failure), so a block of R consecutive r values can be
    # evaluated in parallel and the FIRST acceptable one selected --
    # identical accept/reject semantics, ~R x fewer serial while-loop
    # rounds (under vmap every lane pays the slowest lane's rounds, so
    # this is the difference between ~1-2 rounds and ~tries rounds).
    R = int(min(tries, 8))

    for rep in range(numrep):

        def block(base, _rep=rep, _out=None, _out2=None, _outpos=None):
            ftotals = base + jnp.arange(R, dtype=I32)  # [R]
            rs = _rep + ftotals  # reference: r = rep + ftotal
            cands, oks, hards, _ = jax.vmap(
                lambda rr: _descend(
                    smap, x, take_bucket_idx, target_type, lambda _b: rr
                )
            )(rs)
            in_budget = ftotals < tries
            collides = oks & jax.vmap(
                lambda c: jnp.any((jnp.arange(cap) < _outpos) & (_out == c))
            )(cands)
            rejects = jnp.zeros((R,), bool)
            leafs = jnp.full((R,), ITEM_NONE, I32)
            if recurse_to_leaf:
                is_bucket = cands < 0
                sub_rs = (rs >> (vary_r - 1)) if vary_r else jnp.zeros((R,), I32)
                lf, lok = jax.vmap(
                    lambda c, sr: _leaf_descend_firstn(
                        smap,
                        osd_weight,
                        x,
                        jnp.where(c < 0, c, -1),
                        sr,
                        recurse_tries,
                        _out2,
                        _outpos,
                        stable,
                    )
                )(cands, sub_rs)
                leaf_ok = jnp.where(is_bucket, lok, True)
                cand_leaf = jnp.where(is_bucket, lf, cands)
                rejects = rejects | (oks & ~collides & ~leaf_ok)
                leafs = jnp.where(oks & ~collides & leaf_ok, cand_leaf, leafs)
            if target_type == 0:
                rejects = rejects | (
                    oks & ~collides & jax.vmap(
                        lambda c: _is_out(osd_weight, c, x)
                    )(cands)
                )
            goods = oks & ~collides & ~rejects & in_budget
            hard_stops = hards & in_budget
            stops = goods | hard_stops
            idx = jnp.argmax(stops)
            any_stop = jnp.any(stops)
            is_good = any_stop & goods[idx]
            is_hard = any_stop & ~goods[idx]
            return is_good, is_hard, cands[idx], leafs[idx]

        def cond(st):
            base, done, skip, item, leaf = st
            return (~done) & (~skip) & (base < tries)

        def body(st, _block=block):
            base, _, _, item, leaf = st
            good, hard, cand, lf = _block(
                base, _out=out, _out2=out2, _outpos=outpos
            )
            return (
                base + R,
                good,
                hard,  # skip_rep: abandon this slot entirely
                jnp.where(good, cand, item),
                jnp.where(good, lf, leaf),
            )

        init = (
            jnp.asarray(0, I32),
            FALSE(),
            FALSE(),
            jnp.asarray(ITEM_NONE, I32),
            jnp.asarray(ITEM_NONE, I32),
        )
        _, done, _, item, leaf = lax.while_loop(cond, body, init)
        place = done & (outpos < cap)
        wpos = jnp.minimum(outpos, cap - 1)
        out = out.at[wpos].set(jnp.where(place, item, out[wpos]))
        if recurse_to_leaf:
            out2 = out2.at[wpos].set(jnp.where(place, leaf, out2[wpos]))
        outpos = outpos + place.astype(I32)

    return out, out2, outpos


def _indep_leaf(
    smap: StaticCrushMap,
    osd_weight,
    x,
    bucket_item,
    rep,
    numrep: int,
    parent_r,
    recurse_tries: int,
):
    """chooseleaf-indep recursion: left=1 at slot rep, parent_r threaded.

    r at each level = rep + parent_r + numrep*ftotal' (uniform-divisible
    buckets use (numrep+1)*ftotal').  Returns (leaf, ok).
    """
    bidx0 = jnp.clip(-1 - bucket_item, 0, smap.n_buckets - 1)

    def ftotal_body(ft, st):
        done, failed, leaf = st

        def level_r(bidx):
            uni = (smap.alg[bidx] == ALG_UNIFORM) & (smap.size[bidx] % numrep == 0)
            return jnp.where(
                uni,
                rep + parent_r + (numrep + 1) * ft,
                rep + parent_r + numrep * ft,
            ).astype(I32)

        item, ok, hard, _ = _descend(
            smap, x, bidx0, 0, level_r, empty_is_hard=True
        )
        ok = ok & ~_is_out(osd_weight, item, x)
        newly = (~done) & (~failed) & ok
        # hard failure permanently fails the slot in the reference
        # (out[rep]=NONE, and later rounds skip non-UNDEF slots).
        new_failed = failed | ((~done) & hard)
        return (done | newly, new_failed, jnp.where(newly, item, leaf))

    done, _, leaf = lax.fori_loop(
        jnp.int32(0),
        jnp.int32(recurse_tries),
        ftotal_body,
        (FALSE(), FALSE(), jnp.asarray(ITEM_NONE, I32)),
    )
    return jnp.where(done, leaf, ITEM_NONE), done


def _choose_indep(
    smap: StaticCrushMap,
    osd_weight,
    x,
    take_bucket_idx,
    out_size: int,
    numrep: int,
    target_type: int,
    tries: int,
    recurse_tries: int,
    recurse_to_leaf: bool,
):
    """INDEP (positional/EC) selection; NONE holes on failure.

    Returns (out [out_size], out2 [out_size]).
    """
    out = jnp.full((out_size,), ITEM_UNDEF, I32)
    out2 = jnp.full((out_size,), ITEM_UNDEF, I32)

    def ftotal_body(ftotal, st):
        out, out2 = st
        for rep in range(out_size):
            undef = out[rep] == ITEM_UNDEF

            def level_r(bidx, _rep=rep, _ft=ftotal):
                uni = (smap.alg[bidx] == ALG_UNIFORM) & (
                    smap.size[bidx] % numrep == 0
                )
                return jnp.where(
                    uni, _rep + (numrep + 1) * _ft, _rep + numrep * _ft
                ).astype(I32)

            item, ok, hard, r_final = _descend(
                smap, x, take_bucket_idx, target_type, level_r,
                empty_is_hard=True,
            )
            collide = ok & jnp.any(out == item)
            good = ok & ~collide
            leaf = item
            if recurse_to_leaf:
                is_bucket = item < 0
                lf, lok = _indep_leaf(
                    smap,
                    osd_weight,
                    x,
                    jnp.where(is_bucket, item, -1),
                    jnp.asarray(rep, I32),
                    numrep,
                    r_final,
                    recurse_tries,
                )
                leaf_ok = jnp.where(is_bucket, lok, True)
                leaf = jnp.where(is_bucket, lf, item)
                good = good & leaf_ok
            if target_type == 0:
                good = good & ~_is_out(osd_weight, item, x)
            write_item = undef & good
            write_none = undef & hard  # permanent NONE on hard failure
            newv = jnp.where(
                write_item, item, jnp.where(write_none, ITEM_NONE, out[rep])
            )
            out = out.at[rep].set(newv)
            if recurse_to_leaf:
                newl = jnp.where(
                    write_item, leaf, jnp.where(write_none, ITEM_NONE, out2[rep])
                )
                out2 = out2.at[rep].set(newl)
        return (out, out2)

    out, out2 = lax.fori_loop(
        jnp.int32(0), jnp.int32(tries), ftotal_body, (out, out2)
    )
    out = jnp.where(out == ITEM_UNDEF, ITEM_NONE, out)
    out2 = jnp.where(out2 == ITEM_UNDEF, ITEM_NONE, out2)
    return out, out2


def compile_rule(smap: StaticCrushMap, rule: Rule, result_max: int):
    """Build a jittable ``f(smap, osd_weight, x) -> ([result_max], len)``.

    Specialized on the rule's steps and the map's static shape; vmap/jit
    over x batches.
    """
    tun = smap.tunables
    if tun.choose_local_tries or tun.choose_local_fallback_tries:
        raise NotImplementedError(
            "legacy local-retry tunables are CPU-reference-only; "
            "use the bobtail+ profiles on the TPU path"
        )
    for s in rule.steps:
        if s.op in (OP_SET_CHOOSE_LOCAL_TRIES, OP_SET_CHOOSE_LOCAL_FALLBACK_TRIES):
            if s.arg1 > 0:
                raise NotImplementedError(
                    "legacy local retry tunables not supported on the TPU path"
                )

    def run(smap_: StaticCrushMap, osd_weight, x):
        x = jnp.asarray(x, U32)
        result = jnp.full((result_max,), ITEM_NONE, I32)
        result_len = jnp.asarray(0, I32)
        w: jnp.ndarray | None = None  # working vector after a choose
        wsize = jnp.asarray(0, I32)
        take_static: int | None = None
        # SET_* steps apply sequentially, affecting only later chooses
        # (all values are rule constants, so this stays trace-static).
        choose_tries = tun.choose_total_tries
        chooseleaf_tries = 0
        vary_r = tun.chooseleaf_vary_r
        stable = tun.chooseleaf_stable

        for s in rule.steps:
            if s.op == OP_TAKE:
                take_static = s.arg1
            elif s.op == OP_SET_CHOOSE_TRIES:
                if s.arg1 > 0:
                    choose_tries = s.arg1
            elif s.op == OP_SET_CHOOSELEAF_TRIES:
                if s.arg1 > 0:
                    chooseleaf_tries = s.arg1
            elif s.op == OP_SET_CHOOSELEAF_VARY_R:
                if s.arg1 >= 0:
                    vary_r = s.arg1
            elif s.op == OP_SET_CHOOSELEAF_STABLE:
                if s.arg1 >= 0:
                    stable = s.arg1
            elif s.op in (
                OP_CHOOSE_FIRSTN,
                OP_CHOOSELEAF_FIRSTN,
                OP_CHOOSE_INDEP,
                OP_CHOOSELEAF_INDEP,
            ):
                if take_static is None or take_static >= 0:
                    raise NotImplementedError(
                        "TPU path supports single-TAKE single-choose rules; "
                        "this rule chains chooses or takes a raw device"
                    )
                numrep = s.arg1
                if numrep <= 0:
                    numrep += result_max
                if numrep <= 0:
                    continue
                recurse = s.op in (OP_CHOOSELEAF_FIRSTN, OP_CHOOSELEAF_INDEP)
                firstn = s.op in (OP_CHOOSE_FIRSTN, OP_CHOOSELEAF_FIRSTN)
                bidx = jnp.asarray(-1 - take_static, I32)
                if firstn:
                    recurse_tries_firstn = (
                        chooseleaf_tries
                        if chooseleaf_tries
                        else (1 if tun.chooseleaf_descend_once else choose_tries)
                    )
                    o, o2, osize = _choose_firstn(
                        smap_,
                        osd_weight,
                        x,
                        bidx,
                        numrep,
                        s.arg2,
                        min(numrep, result_max),
                        choose_tries,
                        recurse_tries_firstn,
                        recurse,
                        vary_r,
                        stable,
                    )
                else:
                    out_size = min(numrep, result_max)
                    o, o2 = _choose_indep(
                        smap_,
                        osd_weight,
                        x,
                        bidx,
                        out_size,
                        numrep,
                        s.arg2,
                        choose_tries,
                        chooseleaf_tries if chooseleaf_tries else 1,
                        recurse,
                    )
                    osize = jnp.asarray(out_size, I32)
                w = o2 if recurse else o
                wsize = osize
                take_static = None
            elif s.op == OP_EMIT:
                if w is None:
                    if take_static is not None:
                        # bare take;emit: emit the taken item
                        w = jnp.full((1,), take_static, I32)
                        wsize = jnp.asarray(1, I32)
                        take_static = None
                    else:
                        continue
                pad = result_max - w.shape[0]
                wv = (
                    jnp.concatenate([w, jnp.full((pad,), ITEM_NONE, I32)])
                    if pad > 0
                    else w[:result_max]
                )
                idx = jnp.arange(result_max, dtype=I32)
                shift = idx - result_len
                src = wv[jnp.clip(shift, 0, result_max - 1)]
                write = (shift >= 0) & (shift < wsize)
                result = jnp.where(write, src, result)
                result_len = jnp.minimum(result_len + wsize, result_max)
                w = None
                wsize = jnp.asarray(0, I32)
        return result, result_len

    return run


def smap_signature(smap: StaticCrushMap) -> tuple:
    """Hashable static signature: two maps with equal signatures trace to
    the same program (arrays are traced arguments, not constants)."""
    return (
        smap.n_buckets,
        smap.max_fanout,
        smap.max_devices,
        smap.max_depth,
        smap.tunables,
        tuple(sorted(smap.algs)),
    )


def rule_signature(rule: Rule) -> tuple:
    return tuple((s.op, s.arg1, s.arg2) for s in rule.steps)


_BATCH_CACHE: dict = {}
_MEMO_CAP = 64  # evict oldest beyond this (maps evolve in long processes)


def _memo_put(cache: dict, key, value) -> None:
    if len(cache) >= _MEMO_CAP:
        cache.pop(next(iter(cache)))
    cache[key] = value


def batch_runner(smap: StaticCrushMap, rule: Rule, result_max: int):
    """Cached jitted ``f(smap, osd_weight, xs) -> (results, lens)``.

    Tracing a placement program costs seconds (deep masked loops); the
    program depends only on static shape/tunables/rule structure, so it
    is memoized process-wide by signature.  The persistent XLA cache
    (ceph_tpu.common.compile_cache) extends this across processes.
    """
    key = (smap_signature(smap), rule_signature(rule), result_max)
    fn = _BATCH_CACHE.get(key)
    if fn is None:
        run = compile_rule(smap, rule, result_max)

        @jax.jit
        def fn(smap_, wgt, xs_):
            return jax.vmap(lambda x: run(smap_, wgt, x))(xs_)

        _memo_put(_BATCH_CACHE, key, fn)
    return fn


def batch_do_rule(smap: StaticCrushMap, rule: Rule, xs, osd_weight, result_max: int):
    """vmapped rule execution over a batch of x seeds (jit-compiled).

    Returns (results [n, result_max] int32, lens [n] int32).
    """
    go = batch_runner(smap, rule, result_max)
    return go(smap, jnp.asarray(osd_weight, U32), jnp.asarray(xs, U32))
