from .map import (  # noqa: F401
    ALG_STRAW2,
    ALG_UNIFORM,
    ITEM_NONE,
    Bucket,
    CrushMap,
    DenseCrushMap,
    Rule,
    Step,
    Tunables,
)
