"""Legacy bucket algorithms: straw1 / list / tree builder computations.

The reference keeps per-bucket derived state for its legacy bucket
types (upstream ``src/crush/builder.c``): ``sum_weights`` prefix sums
for list buckets, the float-computed ``straws`` scaling factors for
straw(1) buckets (``crush_calc_straw``), and the binary-tree
``node_weights`` array for tree buckets (``crush_make_tree_bucket``).
This module computes those arrays host-side from the recorded upstream
semantics; the C++ reference tier (``cpp/crush_ref.cpp``) and the test
oracle (:mod:`tests.test_crush_legacy`) consume them.

These algorithms are legacy for a reason — straw1's scaling skews for
>2 distinct weight classes (the motivation for straw2) and list/tree
reorganize data on most topology changes — so no device engine
implements them; maps containing them route to the exact C++ tier
(:func:`ceph_tpu.crush.engine.make_batch_runner`).
"""

from __future__ import annotations

import math

import numpy as np


def list_sum_weights(weights: list[int]) -> list[int]:
    """Prefix sums of item weights (upstream list-bucket sum_weights)."""
    out = []
    acc = 0
    for w in weights:
        acc += int(w)
        out.append(acc)
    return out


def calc_straws(weights: list[int]) -> list[int]:
    """16.16 straw scaling factors (upstream crush_calc_straw).

    Items draw ``(hash & 0xffff) * straws[i]``; the scaling makes the
    argmax winner's probability track the weights for <= 2 distinct
    weight classes (the legacy algorithm's known skew beyond that is
    part of its semantics).  This is the ``straw_calc_version 1``
    algorithm — the fixed builder upstream defaults to; the buggier
    version-0 accumulation is not reproduced.
    """
    size = len(weights)
    straws = [0] * size
    if size == 0:
        return straws
    # stable insertion sort ascending by weight (upstream's loop)
    reverse = [0]
    for i in range(1, size):
        for j in range(i):
            if weights[i] < weights[reverse[j]]:
                reverse.insert(j, i)
                break
        else:
            reverse.append(i)

    numleft = size
    straw = 1.0
    wbelow = 0.0
    lastw = 0.0

    i = 0
    while i < size:
        if weights[reverse[i]] == 0:
            straws[reverse[i]] = 0
            i += 1
            continue
        straws[reverse[i]] = min(int(straw * 0x10000), 0xFFFFFFFF)
        i += 1
        if i == size:
            break
        if weights[reverse[i]] == weights[reverse[i - 1]]:
            continue  # same weight class, same straw
        wbelow += (weights[reverse[i - 1]] - lastw) * numleft
        for j in range(i, size):
            if weights[reverse[j]] == weights[reverse[i]]:
                numleft -= 1
            else:
                break
        wnext = numleft * (weights[reverse[i]] - weights[reverse[i - 1]])
        pbelow = wbelow / (wbelow + wnext)
        straw *= math.pow(1.0 / pbelow, 1.0 / numleft)
        lastw = weights[reverse[i - 1]]
    return straws


def tree_depth(size: int) -> int:
    """Depth of the tree covering ``size`` leaves (upstream calc_depth)."""
    if size == 0:
        return 0
    depth = 1
    t = size - 1
    while t:
        t >>= 1
        depth += 1
    return depth


def tree_node_count(size: int) -> int:
    return 1 << tree_depth(size)


def _height(n: int) -> int:
    h = 0
    while n and (n & 1) == 0:
        h += 1
        n >>= 1
    return h


def _parent(n: int) -> int:
    h = _height(n)
    if n & (1 << (h + 1)):
        return n - (1 << h)
    return n + (1 << h)


def tree_node_weights(weights: list[int]) -> list[int]:
    """Node-weight array for a tree bucket: item i at node 2i+1, each
    internal node the sum of its subtree (upstream crush_make_tree_bucket)."""
    size = len(weights)
    if size == 0:
        return [0]
    depth = tree_depth(size)
    num_nodes = 1 << depth
    node_w = [0] * num_nodes
    root = num_nodes >> 1
    for i, w in enumerate(weights):
        node = 2 * i + 1
        node_w[node] = int(w)
        while node != root:
            node = _parent(node)
            node_w[node] += int(w)
    return node_w


def aux_arrays(
    algs: np.ndarray,
    sizes: np.ndarray,
    weights: np.ndarray,
) -> tuple[np.ndarray, np.ndarray, int] | None:
    """Per-bucket aux table for a dense map: column-packed
    (straws-or-sums [n, max_fanout], tree_nodes [n, max_tree_nodes],
    max_tree_nodes); None when no legacy algs are present."""
    from .map import ALG_LIST, ALG_STRAW, ALG_TREE

    n, max_fanout = weights.shape
    present = set(int(a) for a in np.unique(algs[sizes > 0]))
    if not present & {ALG_LIST, ALG_STRAW, ALG_TREE}:
        return None
    max_nodes = 1
    for b in range(n):
        if algs[b] == ALG_TREE and sizes[b] > 0:
            max_nodes = max(max_nodes, tree_node_count(int(sizes[b])))
    scaled = np.zeros((n, max_fanout), np.uint32)  # straws or sum_weights
    tree_w = np.zeros((n, max_nodes), np.uint32)
    for b in range(n):
        sz = int(sizes[b])
        if sz == 0:
            continue
        ws = [int(w) for w in weights[b, :sz]]
        if algs[b] == ALG_LIST:
            scaled[b, :sz] = list_sum_weights(ws)
        elif algs[b] == ALG_STRAW:
            scaled[b, :sz] = calc_straws(ws)
        elif algs[b] == ALG_TREE:
            nw = tree_node_weights(ws)
            tree_w[b, : len(nw)] = nw
    return scaled, tree_w, max_nodes
