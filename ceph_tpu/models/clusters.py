"""Synthetic cluster-map builders (the framework's "model zoo").

Equivalents of the reference's synthetic map constructors used
throughout its tests and tools (upstream ``OSDMap::build_simple`` in
``src/osd/OSDMap.cc`` and ``crushtool --build``): generate flat or
multi-tier CRUSH hierarchies from device counts, for tests and
benchmarks.
"""

from __future__ import annotations

from ceph_tpu.crush.map import ALG_STRAW2, CrushMap, Tunables

W1 = 0x10000  # weight 1.0 in 16.16


def build_flat(n_osds: int, weight: int = W1, alg: int = ALG_STRAW2,
               tunables: Tunables | None = None) -> CrushMap:
    """One root bucket holding all OSDs."""
    m = CrushMap(tunables)
    m.add_type(1, "root")
    root = m.add_bucket("default", "root", alg=alg)
    for o in range(n_osds):
        m.insert_item(root.id, o, weight)
    m.make_replicated_rule("replicated_rule", "default", "osd")
    return m


def build_hierarchy(
    spec: list[tuple[str, int]],
    osds_per_leaf: int,
    weight: int = W1,
    alg: int = ALG_STRAW2,
    tunables: Tunables | None = None,
    failure_domain: str | None = None,
) -> CrushMap:
    """Multi-tier map.

    ``spec`` is outer-to-inner, e.g. ``[("rack", 4), ("host", 8)]`` with
    ``osds_per_leaf=4`` builds root -> 4 racks -> 8 hosts each -> 4 osds
    each (128 OSDs).  A replicated rule over ``failure_domain`` (default:
    the innermost non-osd tier) is added.
    """
    m = CrushMap(tunables)
    m.add_type(1, "root")
    for lvl, (tname, _) in enumerate(spec):
        m.add_type(len(spec) + 1 - lvl, tname)

    osd = [0]

    def build_level(lvl: int, prefix: str) -> tuple[int, int]:
        """Returns (bucket_id, subtree weight)."""
        tname = spec[lvl][0] if lvl < len(spec) else None
        if tname is None:
            raise AssertionError
        b = m.add_bucket(f"{tname}{prefix}", tname, alg=alg)
        total = 0
        if lvl == len(spec) - 1:
            for _ in range(osds_per_leaf):
                m.insert_item(b.id, osd[0], weight)
                osd[0] += 1
                total += weight
        else:
            for j in range(spec[lvl + 1][1]):
                cid, cw = build_level(lvl + 1, f"{prefix}_{j}")
                m.insert_item(b.id, cid, cw)
                total += cw
        return b.id, total

    root = m.add_bucket("default", "root", alg=alg)
    for i in range(spec[0][1]):
        cid, cw = build_level(0, f"{i}")
        m.insert_item(root.id, cid, cw)
    fd = failure_domain or spec[-1][0]
    m.make_replicated_rule("replicated_rule", "default", fd)
    return m


def build_osdmap(
    n_osds: int,
    pg_num: int = 64,
    size: int = 3,
    pool_kind: str = "replicated",
    osds_per_host: int = 4,
    hosts_per_rack: int = 8,
):
    """Synthetic OSDMap (the ``OSDMap::build_simple`` analog): simple
    rack/host/osd CRUSH tree, one pool, all OSDs up+in."""
    from ceph_tpu.osdmap.map import OSDMap, Pool

    crush = build_simple(n_osds, osds_per_host, hosts_per_rack)
    if pool_kind == "erasure":
        crush.make_erasure_rule("erasure_rule", "default", "host")
    m = OSDMap(crush)
    for o in range(n_osds):
        m.add_osd(o)
    rule = crush.rule_by_name(
        "erasure_rule" if pool_kind == "erasure" else "replicated_rule"
    )
    m.add_pool(
        Pool(
            id=1,
            name="pool1",
            kind=pool_kind,
            size=size,
            pg_num=pg_num,
            pgp_num=pg_num,
            crush_rule=rule.id,
        )
    )
    return m


def build_simple(n_osds: int, osds_per_host: int = 4, hosts_per_rack: int = 8,
                 tunables: Tunables | None = None) -> CrushMap:
    """root -> racks -> hosts -> osds sized to cover ``n_osds`` devices."""
    import math

    n_hosts = math.ceil(n_osds / osds_per_host)
    n_racks = max(1, math.ceil(n_hosts / hosts_per_rack))
    m = CrushMap(tunables)
    m.add_type(1, "root")
    m.add_type(2, "rack")
    m.add_type(3, "host")
    root = m.add_bucket("default", "root")
    osd = 0
    for r in range(n_racks):
        rack = m.add_bucket(f"rack{r}", "rack")
        rack_w = 0
        for h in range(hosts_per_rack):
            if osd >= n_osds:
                break
            host = m.add_bucket(f"host{r}_{h}", "host")
            host_w = 0
            for _ in range(osds_per_host):
                if osd >= n_osds:
                    break
                m.insert_item(host.id, osd, W1)
                host_w += W1
                osd += 1
            m.insert_item(rack.id, host.id, host_w)
            rack_w += host_w
        m.insert_item(root.id, rack.id, rack_w)
    m.make_replicated_rule("replicated_rule", "default", "host")
    return m


def build_skewed(
    n_osds: int,
    seed: int = 0,
    tunables: Tunables | None = None,
) -> CrushMap:
    """Deep, heterogeneous hierarchy: root -> dcs -> racks -> hosts ->
    osds with ragged fanouts and mixed device weights (0.5x-4x).

    The uniform ``build_simple`` topology never stresses straw2 retry
    divergence or the balancer's weight handling; this one does — use
    it wherever "realistic cluster" matters (benches, property tests).
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    m = CrushMap(tunables)
    m.add_type(1, "root")
    m.add_type(2, "dc")
    m.add_type(3, "rack")
    m.add_type(4, "host")
    root = m.add_bucket("default", "root")
    osd = 0
    dc_i = rack_i = host_i = 0
    while osd < n_osds:
        dc = m.add_bucket(f"dc{dc_i}", "dc")
        dc_i += 1
        dc_w = 0
        for _ in range(int(rng.integers(2, 5))):
            if osd >= n_osds:
                break
            rack = m.add_bucket(f"rack{rack_i}", "rack")
            rack_i += 1
            rack_w = 0
            for _ in range(int(rng.integers(2, 7))):
                if osd >= n_osds:
                    break
                host = m.add_bucket(f"host{host_i}", "host")
                host_i += 1
                host_w = 0
                for _ in range(int(rng.integers(2, 9))):
                    if osd >= n_osds:
                        break
                    w = int(rng.integers(0x8000, 0x40000))  # 0.5x-4x
                    m.insert_item(host.id, osd, w)
                    host_w += w
                    osd += 1
                m.insert_item(rack.id, host.id, host_w)
                rack_w += host_w
            m.insert_item(dc.id, rack.id, rack_w)
            dc_w += rack_w
        m.insert_item(root.id, dc.id, dc_w)
    m.make_replicated_rule("replicated_rule", "default", "host")
    return m


def build_skewed_osdmap(
    n_osds: int,
    pg_num: int = 1024,
    size: int = 3,
    seed: int = 0,
):
    """OSDMap over :func:`build_skewed` (one replicated pool)."""
    from ceph_tpu.osdmap.map import OSDMap, Pool

    crush = build_skewed(n_osds, seed=seed)
    m = OSDMap(crush)
    for o in range(n_osds):
        m.add_osd(o)
    rule = crush.rule_by_name("replicated_rule")
    m.add_pool(
        Pool(
            id=1,
            name="pool1",
            kind="replicated",
            size=size,
            pg_num=pg_num,
            pgp_num=pg_num,
            crush_rule=rule.id,
        )
    )
    return m
