from .clusters import build_flat, build_hierarchy, build_simple  # noqa: F401
