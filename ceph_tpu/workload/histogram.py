"""Device-resident log-bucketed histograms for latency percentiles.

Estimating tail latency over millions of ops per step rules out
sorting or host round-trips: the device step scatter-adds each op into
a power-of-two bucket ladder (``edge[i] = lat_min * 2**i``), the
[n_buckets] count vector is psum'd across the mesh so every rank holds
the identical distribution, and the host merges counts into
p50/p95/p99 with one O(n_buckets) pass.  Relative error is bounded by
the bucket ratio (2x worst case, halved by the in-bucket
interpolation below) — the same trade HDR-style histograms make.

The ladder doubles as the Prometheus histogram schema: ``edges()``
are the ``le`` upper bounds the perf-counter registry's
``TYPE_HISTOGRAM`` renders cumulatively.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

I32 = jnp.int32
F32 = jnp.float32

#: default ladder: 24 buckets from 0.0625 ms, topping out ~9 minutes
N_BUCKETS = 24
LAT_MIN_MS = 0.0625


def bucket_edges(
    n_buckets: int = N_BUCKETS, lat_min: float = LAT_MIN_MS
) -> np.ndarray:
    """Upper bounds of the log2 ladder (host float64, ``le`` values)."""
    return lat_min * np.exp2(np.arange(1, n_buckets + 1, dtype=np.float64))


def bucketize(values, n_buckets: int = N_BUCKETS, lat_min: float = LAT_MIN_MS):
    """Traced: value -> bucket index.  Values at or below ``lat_min``
    land in bucket 0; anything past the top edge clips into the last
    bucket (the overflow slot)."""
    v = jnp.maximum(values.astype(F32), jnp.float32(lat_min))
    idx = jnp.floor(jnp.log2(v / jnp.float32(lat_min))).astype(I32)
    return jnp.clip(idx, 0, n_buckets - 1)


def scatter_hist(idx, weight, n_buckets: int = N_BUCKETS):
    """Traced: scatter-add ``weight`` (i32, 0 to drop an op) into the
    [n_buckets] count vector."""
    return jnp.zeros(n_buckets, I32).at[idx].add(weight)


def percentile(counts: np.ndarray, edges: np.ndarray, q: float) -> float:
    """Host-side merge: the ``q``-quantile (0..1) of a bucketed
    distribution, linearly interpolated inside the bucket.  Zero-total
    histograms report 0.0."""
    counts = np.asarray(counts, np.int64)
    total = int(counts.sum())
    if total == 0:
        return 0.0
    rank = q * total
    cum = np.cumsum(counts)
    i = int(np.searchsorted(cum, rank, side="left"))
    i = min(i, len(counts) - 1)
    lo = float(edges[i - 1]) if i > 0 else float(edges[0]) / 2.0
    hi = float(edges[i])
    before = int(cum[i - 1]) if i > 0 else 0
    inside = int(counts[i])
    frac = (rank - before) / inside if inside else 1.0
    return lo + (hi - lo) * min(max(frac, 0.0), 1.0)


def percentiles(
    counts: np.ndarray, edges: np.ndarray, qs=(0.5, 0.95, 0.99)
) -> tuple[float, ...]:
    return tuple(percentile(counts, edges, q) for q in qs)


def count_at_least(counts: np.ndarray, edges: np.ndarray, floor: float) -> int:
    """Ops in buckets whose *lower* edge is >= ``floor`` — the
    conservative (never over-counting) slow-op estimate the SLO layer
    grades."""
    counts = np.asarray(counts, np.int64)
    lowers = np.concatenate(([0.0], np.asarray(edges)[:-1]))
    return int(counts[lowers >= floor].sum())
