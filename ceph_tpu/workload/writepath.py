"""The online write path as a per-epoch encode stage in the superstep.

:class:`WritepathDriver` wraps an
:class:`~ceph_tpu.recovery.superstep.EpochDriver` and extends its
``lax.scan`` body with the data plane the traffic engine only modeled:
each epoch's committed client writes — the SAME routed, classified op
batch the traffic step counts (identical salt, identical ``_route``
predicates against the post-peering survivor masks) — are compacted
into a fixed-shape write batch and absorbed by the device-resident
stripe buffer (:mod:`ceph_tpu.ec.online`).  Full-stripe writes batch
through the codec's compiled XOR-schedule encoder; small overwrites
become read-modify-write parity deltas.  The epoch lanes the wrapped
driver emits are bit-identical to an unwrapped run (the write stage
reads cluster state, never writes it), and the buffer rides the scan
carry, so checkpoint snapshots of ``(ClusterState, StripeBufferState)``
resume bit-equal with a warm cache.

Compile-once discipline: the write-batch buffer is sized to the
power-of-two bucket of ``max_writes`` and the per-epoch write cap is a
*traced* scalar, so varying write-batch sizes inside one bucket reuse
ONE compiled program with zero in-scan host transfers (the
``online_write_batch`` nonregression scenario pins this).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from ..ec.online import (
    WP_LANES,
    ParityDeltaEngine,
    StripeBufferState,
    empty_stripe_buffer,
    register_stripe_cache,
    stripe_buffer_step,
    summarize_buffer,
    writepath_counters,
)
from ..recovery.superstep import _SALT_STEP, _SERIES_FIELDS, EpochSeries

I32 = jnp.int32
U32 = jnp.uint32

#: decorrelate the stripe-index, chunk-index, full-stripe and payload
#: coins from each other and from the routing/skew hashes
_STRIPE_SALT = np.uint32(0x7FEB352D)
_CHUNK_SALT = np.uint32(0x846CA68B)
_FULL_SALT = np.uint32(0x9E485565)
_SEED_SALT = np.uint32(0xE2D0D4CB)


def _pow2_bucket(n: int) -> int:
    """The power-of-two batch bucket holding ``n`` write slots."""
    return 1 << max(int(n) - 1, 0).bit_length() if n > 1 else 1


def default_bitmatrix(k: int, m: int, w: int | None = None):
    """The write-path codec for a ``k+m`` pool: a minimal-density
    RAID-6 code when ``m == 2`` (liberation — the cheapest XOR
    programs), else the cauchy-good w=8 expansion.  Returns
    ``(bitmatrix, w)``."""
    from ..ec import gf, gfw

    if int(m) == 2:
        if w is None:
            w = next(p for p in (7, 11, 13, 17, 19, 23)
                     if p >= int(k))
        return gfw.liberation_bitmatrix(int(k), int(w)), int(w)
    return gf.matrix_to_bitmatrix(
        gf.cauchy_good_matrix(int(k), int(m))
    ), 8


@dataclass(frozen=True)
class WritepathSeries:
    """Per-epoch write-path lanes (``WP_LANES`` order), host numpy —
    the stripe buffer's journal payload and the differential test's
    comparison surface."""

    lanes: np.ndarray  # i64 [n, len(WP_LANES)]

    def __len__(self) -> int:
        return int(self.lanes.shape[0])

    @classmethod
    def from_device(cls, wrows) -> "WritepathSeries":
        return cls(lanes=np.asarray(jax.device_get(wrows)))

    @classmethod
    def concat(cls, parts: list["WritepathSeries"]) -> "WritepathSeries":
        if len(parts) == 1:
            return parts[0]
        return cls(lanes=np.concatenate([p.lanes for p in parts]))

    def lane(self, name: str) -> np.ndarray:
        return self.lanes[:, WP_LANES.index(name)]

    def totals(self) -> dict:
        tot = self.lanes.sum(axis=0) if len(self) else np.zeros(
            len(WP_LANES), np.int64
        )
        return {n: int(v) for n, v in zip(WP_LANES, tot)}

    def diff(self, other: "WritepathSeries") -> list[str]:
        """Lane names where the two series differ bit-for-bit."""
        if self.lanes.shape != other.lanes.shape:
            return ["<shape>"]
        return [
            n for i, n in enumerate(WP_LANES)
            if not np.array_equal(self.lanes[:, i], other.lanes[:, i])
        ]


class WritepathDriver:
    """Online EC write path over a built epoch driver.

    ``n_sets`` x ``ways`` is the stripe-buffer geometry (``n_sets`` a
    power of two); ``stripes_per_pg`` shapes the stripe key space
    (``key = pg * stripes_per_pg + stripe``); ``full_permille`` is the
    full-stripe share of committed writes (the rest are single-chunk
    small overwrites); ``groups`` scales the chunk size
    (``chunk_bytes = groups * w * packetsize``).  ``max_writes`` caps
    the per-epoch write batch; the batch buffer is its power-of-two
    bucket and the live cap is traced, so any cap inside the bucket
    runs through one compiled scan.
    """

    def __init__(
        self,
        driver,
        *,
        bitmatrix: np.ndarray | None = None,
        w: int | None = None,
        packetsize: int = 8,
        groups: int = 1,
        n_sets: int = 16,
        ways: int = 4,
        stripes_per_pg: int = 4,
        full_permille: int = 125,
        max_writes: int | None = None,
        cache=None,
        name: str = "writepath",
    ):
        self.driver = driver
        if packetsize % 4:
            raise ValueError(
                f"packetsize must be u32-aligned on the device path, "
                f"got {packetsize}"
            )
        if bitmatrix is None:
            k = int(driver.k)
            m = max(int(driver.size) - k, 1)
            bitmatrix, w = default_bitmatrix(k, m, w)
        self.engine = ParityDeltaEngine(
            np.asarray(bitmatrix), w=int(w or 8),
            packetsize=int(packetsize), cache=cache, name=name,
        )
        self.k = self.engine.k
        self.m = self.engine.m
        self.w = self.engine.w
        self.packetsize = self.engine.packetsize
        self.groups = int(groups)
        self.chunk_bytes = self.groups * self.w * self.packetsize
        #: u32 words per packed row (packetsize is u32-aligned, so the
        #: packet layout is a pure reshape — no tail pad)
        self.words = self.groups * (self.packetsize // 4)
        enc = self.engine.full_encoder()
        self.schedule = enc.schedule
        self._steps_dev = jnp.asarray(self.schedule.steps)
        self.n_sets = int(n_sets)
        self.ways = int(ways)
        self.stripes_per_pg = int(stripes_per_pg)
        self.full_permille = int(full_permille)
        self.max_writes = int(
            max_writes if max_writes is not None else driver.n_ops
        )
        self.batch_size = _pow2_bucket(self.max_writes)
        from ..analysis import runtime_guard

        if runtime_guard.bucket_checks_enabled():
            runtime_guard.assert_bucketed(
                "writepath batch bucket", self.batch_size
            )
        self._init_buf = empty_stripe_buffer(
            self.n_sets, self.ways, self.k * self.w, self.m * self.w,
            self.words,
        )
        self.name = str(name)
        self.pc = writepath_counters()
        self._scan_fn = None
        self._flight_scan_fn = None
        self._one_fn = None
        self.final_state = None
        self.final_buf: StripeBufferState | None = None
        #: live recorder carry after the most recent flight-on run
        self.flight = None
        register_stripe_cache(self)

    # -- the per-epoch write batch (drawn from the traffic step) -------

    def _write_batch(self, state, step, cap):
        """Compact this epoch's committed writes into the fixed-shape
        batch: the SAME ids, salt and ``_route`` predicates the traffic
        step counted, so ``sum(valid)`` (uncapped) equals the epoch
        row's ``writes`` lane."""
        from .traffic import _route, _skew_ids

        drv = self.driver
        n_ops = drv.n_ops
        B = self.batch_size
        salt = drv.salt_base + step.astype(U32) * _SALT_STEP
        ids = jnp.arange(n_ops, dtype=U32)
        mix = drv._mix
        if mix is not None and mix.hot_permille > 0:
            ids = _skew_ids(
                ids, salt, mix.hot_permille, mix.hot_objects
            )
        pg_b = np.uint32(drv.pg_num)
        pg_bmask = np.uint32(
            (1 << max(drv.pg_num - 1, 1).bit_length()) - 1
        )
        pg, _prim, is_write, blocked, _deg, _cost = _route(
            state.survivor_mask, state.n_alive, state.acting_primary,
            ids, salt, pg_b, pg_bmask, np.int32(drv.k),
            np.int32(drv.size), np.int32(drv.min_size),
            np.int32(drv.write_permille),
        )
        okw = ~blocked & is_write
        pos = jnp.cumsum(okw.astype(I32)) - 1
        lim = jnp.minimum(cap.astype(I32), jnp.int32(B))
        take = okw & (pos < lim)
        # rejected lanes all dump identical sentinels on scratch slot B,
        # so the scatter is order-free and fully deterministic
        slot = jnp.where(take, pos, jnp.int32(B))
        stripe = (
            crush_hash32_2(ids, salt ^ _STRIPE_SALT)
            % jnp.uint32(self.stripes_per_pg)
        ).astype(I32)
        key = pg * np.int32(self.stripes_per_pg) + stripe
        chunk = (
            crush_hash32_2(ids, salt ^ _CHUNK_SALT)
            % jnp.uint32(self.k)
        ).astype(I32)
        full = (
            (crush_hash32_2(ids, salt ^ _FULL_SALT)
             % jnp.uint32(1000)).astype(I32)
            < np.int32(self.full_permille)
        )
        seed = crush_hash32_2(ids, salt ^ _SEED_SALT)

        def compact(vals, fill):
            out = jnp.full((B + 1,), fill, vals.dtype)
            return out.at[slot].set(
                jnp.where(take, vals, fill)
            )[:B]

        bkeys = compact(key, np.int32(-1))
        bchunks = compact(chunk, np.int32(0))
        bfulls = compact(full, False)
        bseeds = compact(seed, np.uint32(0))
        bvalid = compact(
            jnp.ones(n_ops, bool), False
        ) & (bkeys >= 0)
        n_writes = jnp.sum(okw.astype(I32))
        return bkeys, bchunks, bfulls, bseeds, bvalid, n_writes

    # -- the extended epoch body ---------------------------------------

    def _wp_epoch(self, carry, step, cap):
        state, buf = carry
        state, row = self.driver._epoch_step(state, step)
        bkeys, bchunks, bfulls, bseeds, bvalid, _nw = (
            self._write_batch(state, step, cap)
        )
        buf, wrow = stripe_buffer_step(
            buf, self._steps_dev, self.schedule.n_out,
            self.schedule.n_bufs, self.k, self.w,
            bkeys, bchunks, bfulls, bseeds, bvalid,
        )
        return (state, buf), (row, wrow)

    def _wp_epoch_flight(self, carry, step, cap):
        """The flight-recorder twin of :meth:`_wp_epoch`: the traced
        epoch body plus the in-scan ring write.  The stripe lanes land
        in the ring from ``wrow``, so a writepath flight row carries
        live cache telemetry where the bare superstep records zeros."""
        from ..obs.flight import flight_record

        state, buf, fs = carry
        state, row, extras = self.driver._epoch_step_traced(
            state, step
        )
        bkeys, bchunks, bfulls, bseeds, bvalid, _nw = (
            self._write_batch(state, step, cap)
        )
        buf, wrow = stripe_buffer_step(
            buf, self._steps_dev, self.schedule.n_out,
            self.schedule.n_bufs, self.k, self.w,
            bkeys, bchunks, bfulls, bseeds, bvalid,
        )
        fs = flight_record(
            fs, self.driver._flight_row(row, extras, wrow=wrow)
        )
        return (state, buf, fs), (row, wrow)

    # -- drivers -------------------------------------------------------

    def compile_writepath(self):
        """The ONE jitted program: ``(state, buf, steps, cap) ->
        (state, buf, rows, wrows)`` — the wrapped driver's epoch scan
        with the encode stage fused in.  ``cap`` is traced, so every
        write-batch size inside the bucket reuses this executable."""
        if self._scan_fn is None:

            @jax.jit
            def scan_fn(state, buf, steps, cap):
                def body(carry, step):
                    return self._wp_epoch(carry, step, cap)

                (state, buf), (rows, wrows) = jax.lax.scan(
                    body, (state, buf), steps
                )
                return state, buf, rows, wrows

            self._scan_fn = scan_fn
        return self._scan_fn

    def compile_writepath_flight(self):
        """The flight-on program: ``(state, buf, fs, steps, cap) ->
        (state, buf, fs, rows, wrows)`` — same epoch math, ring riding
        the carry (the 18 epoch lanes and every WP lane stay bit-equal
        to the plain scan; only the extra telemetry carry differs)."""
        if self._flight_scan_fn is None:

            @jax.jit
            def scan_fn(state, buf, fs, steps, cap):
                def body(carry, step):
                    return self._wp_epoch_flight(carry, step, cap)

                (state, buf, fs), (rows, wrows) = jax.lax.scan(
                    body, (state, buf, fs), steps
                )
                return state, buf, fs, rows, wrows

            self._flight_scan_fn = scan_fn
        return self._flight_scan_fn

    def _note_totals(self, wseries: WritepathSeries) -> None:
        self.engine.pc_inc(self.pc, wseries.lanes.sum(axis=0))

    def run_superstep(
        self, n_epochs: int, *, cap: int | None = None,
        snapshot_every: int = 0, pull: bool = True,
        buf: StripeBufferState | None = None, start_epoch: int = 0,
        journal=None,
    ):
        """Drive the fused scan; mirrors
        :meth:`EpochDriver.run_superstep` (host exits only at snapshot
        boundaries; ``pull=False`` returns device-resident
        ``(state, buf, rows, wrows)``).  With the wrapped driver's
        flight recorder on, the ring rides the carry and drains into
        ``journal`` at each boundary (``self.flight`` afterwards)."""
        flight_on = bool(getattr(self.driver, "flight_on", False))
        scan_fn = (
            self.compile_writepath_flight() if flight_on
            else self.compile_writepath()
        )
        state = self.driver._init_state
        buf = self._init_buf if buf is None else buf
        fs = self.driver._init_flight if flight_on else None
        cap_t = jnp.int32(self.max_writes if cap is None else cap)
        n_epochs = int(n_epochs)
        if n_epochs <= 0:
            if flight_on:
                state, buf, fs, rows, wrows = scan_fn(
                    state, buf, fs, jnp.arange(0, dtype=I32), cap_t
                )
                self.flight = fs
            else:
                state, buf, rows, wrows = scan_fn(
                    state, buf, jnp.arange(0, dtype=I32), cap_t
                )
            self.final_state, self.final_buf = state, buf
            self.driver.final_state = state
            if not pull:
                return state, buf, rows, wrows
            return (
                EpochSeries.from_device(rows),
                WritepathSeries.from_device(wrows),
            )
        chunk = int(snapshot_every) or n_epochs
        parts: list[EpochSeries] = []
        wparts: list[WritepathSeries] = []
        dev = None
        start = int(start_epoch)
        end_at = start + n_epochs
        while start < end_at:
            size = min(chunk, end_at - start)
            steps = jnp.arange(start, start + size, dtype=I32)
            if flight_on:
                state, buf, fs, rows, wrows = scan_fn(
                    state, buf, fs, steps, cap_t
                )
                self.flight = fs
                if journal is not None:
                    from ..obs.flight import journal_drain

                    journal_drain(
                        journal, fs, chunk_start=start,
                        source="writepath",
                    )
            else:
                state, buf, rows, wrows = scan_fn(
                    state, buf, steps, cap_t
                )
            if pull:
                parts.append(EpochSeries.from_device(rows))
                wparts.append(WritepathSeries.from_device(wrows))
            else:
                dev = (rows, wrows)
            start += size
        self.final_state, self.final_buf = state, buf
        self.driver.final_state = state
        if not pull:
            return state, buf, dev[0], dev[1]
        wseries = WritepathSeries.concat(wparts)
        self._note_totals(wseries)
        return EpochSeries.concat(parts), wseries

    def run_staged(
        self, n_epochs: int, *, cap: int | None = None
    ):
        """The differential reference: the SAME fused epoch body,
        launched once per epoch with host pulls between launches —
        bit-equal to the scan by construction."""
        if self._one_fn is None:

            @jax.jit
            def one_fn(state, buf, step, cap):
                (state, buf), (row, wrow) = self._wp_epoch(
                    (state, buf), step, cap
                )
                return state, buf, row, wrow

            self._one_fn = one_fn
        state = self.driver._init_state
        buf = self._init_buf
        cap_t = jnp.int32(self.max_writes if cap is None else cap)
        rows, wrows = [], []
        for e in range(int(n_epochs)):
            state, buf, row, wrow = self._one_fn(
                state, buf, jnp.int32(e), cap_t
            )
            rows.append(tuple(np.asarray(v) for v in row))
            wrows.append(np.asarray(wrow))
        self.final_state, self.final_buf = state, buf
        self.driver.final_state = state
        series = EpochSeries(**{
            f: np.stack([r[i] for r in rows])
            for i, f in enumerate(_SERIES_FIELDS)
        }) if rows else EpochSeries(**{
            f: np.zeros((0,)) for f in _SERIES_FIELDS
        })
        wseries = WritepathSeries(
            lanes=np.stack(wrows) if wrows
            else np.zeros((0, len(WP_LANES)), np.int64)
        )
        return series, wseries

    # -- observability -------------------------------------------------

    def dump_stripe_cache(self) -> dict:
        """This driver's panel for the ``dump_stripe_cache`` admin
        hook: buffer occupancy + counters + the footprint-program
        cache."""
        buf = self.final_buf if self.final_buf is not None else (
            self._init_buf
        )
        return {
            "name": self.name,
            **summarize_buffer(buf),
            "schedule_cache": self.engine.cache.dump(),
        }


# deferred to module bottom: core.hashes is import-light, but keeping
# the jnp-facing import near its sole non-batch consumer documents the
# seam the batch builder shares with the traffic router
from ..core.hashes import crush_hash32_2  # noqa: E402


# ---------------------------------------------------------------------------
# checkpoint integration: durable snapshots of (cluster, stripe buffer)


def checkpointed_writepath(
    wdrv: WritepathDriver,
    n_epochs: int,
    *,
    store,
    snapshot_every: int = 0,
    cap: int | None = None,
    crashes=(),
):
    """:meth:`WritepathDriver.run_superstep` with a durable snapshot at
    every boundary and resume-from-store on entry — the
    :func:`~ceph_tpu.recovery.checkpoint.checkpointed_superstep`
    contract extended to the write path: each boundary commits the
    ``(ClusterState, StripeBufferState)`` pytree plus both series so
    far, so a killed run resumes with a WARM stripe buffer and lands
    bit-equal (exact :meth:`EpochSeries.diff` and
    :meth:`WritepathSeries.diff`) to an uninterrupted run."""
    from ..recovery.checkpoint import _aligned_end, _CrashSchedule

    n_epochs = int(n_epochs)
    every = int(snapshot_every) or max(n_epochs, 1)
    sched = _CrashSchedule(crashes)
    scan_fn = wdrv.compile_writepath()
    cap_t = jnp.int32(wdrv.max_writes if cap is None else cap)
    template = (wdrv.driver._init_state, wdrv._init_buf)
    resume = store.load_latest(template, with_series=True)
    if resume is None:
        (state, buf), start, cols, wlanes = template, 0, None, None
    else:
        meta, (state, buf), series = resume
        start = int(meta.get("next_epoch", 0))
        cols = (
            {f: series[f] for f in _SERIES_FIELDS} if series else None
        )
        wlanes = series.get("wp_lanes") if series else None
    if start == 0:
        cols, wlanes = None, None
    while start < n_epochs:
        end = _aligned_end(start, n_epochs, every)
        steps = jnp.arange(start, end, dtype=I32)
        state, buf, rows, wrows = scan_fn(state, buf, steps, cap_t)
        part = EpochSeries.from_device(rows)
        wpart = WritepathSeries.from_device(wrows)
        cols = {
            f: (np.concatenate([cols[f], getattr(part, f)])
                if cols is not None else getattr(part, f))
            for f in _SERIES_FIELDS
        }
        wlanes = (
            np.concatenate([wlanes, wpart.lanes])
            if wlanes is not None else wpart.lanes
        )
        sched.fire(end, "before")
        during = sched.due(end, "during")
        if during is not None:
            store._crash_hook = lambda phase: during.fire()
        try:
            store.save(
                (state, buf),
                meta={"next_epoch": end, "n_epochs": n_epochs},
                series={**cols, "wp_lanes": wlanes},
            )
        finally:
            store._crash_hook = None
        sched.fire(end, "after")
        start = end
    wdrv.final_state, wdrv.final_buf = state, buf
    wdrv.driver.final_state = state
    if cols is None:
        state, buf, rows, wrows = scan_fn(
            *template, jnp.arange(0, 0, dtype=I32), cap_t
        )
        return (
            EpochSeries.from_device(rows),
            WritepathSeries.from_device(wrows),
        )
    wseries = WritepathSeries(lanes=wlanes)
    wdrv._note_totals(wseries)
    return EpochSeries(**cols), wseries
