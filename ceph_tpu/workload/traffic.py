"""Vmapped client workload generator against the live degraded map.

The paper's north star is a cluster *serving* millions of ops/s while
chaos and recovery run — so health must be judged on what clients
experience, not a PG-serviceability proxy (arXiv:1709.05365: the
dominant production cost of online EC is foreground/recovery
interference).  One device step routes a fixed-shape batch of object
reads/writes end to end:

- **route**: object id -> ``crush_hash32_2`` -> ``ceph_stable_mod`` ->
  PG (the client-side ``ceph_object_locator_to_pg``), then a gather
  against the peering pass's per-PG survivor mask / acting primary —
  the same compiled CRUSH/OSDMap state recovery works from, at the
  epoch chaos last touched.
- **classify**: every op lands in exactly one outcome from the
  survivor bitmask — *served* (full redundancy), *degraded-served*
  (readable, but below ``size`` survivors: EC reconstruct on the read
  path), or *blocked-on-inactive* (reads below ``k`` survivors, writes
  below ``min_size`` live acting members — the reference stalls both).
- **queue model**: per-OSD load is scatter-added at the acting primary
  (reads 1 unit, degraded reads ``k`` — the reconstruct fan-in — and
  writes ``size``), normalized to per-OSD capacity, plus a uniform
  recovery-utilization term derived from the observed inter-sample
  repair bandwidth (rateless-style load accounting, arXiv:1804.10331).
  Latency is M/D/1-shaped: ``service * amp * (1 + rho/(1-rho))`` with
  rho clipped below saturation.
- **aggregate**: outcome counts, latency and queue-depth log-bucket
  histograms (:mod:`ceph_tpu.workload.histogram`), sums, and the peak
  OSD utilization — O(n_buckets) outputs regardless of batch size.

Under a mesh the op axis splits across devices (each chip generates
its id slice from ``axis_index``, exactly the placement-sim recipe)
and every output is psum'd, so all ranks agree bit-exactly on the
histograms (asserted by the two-process test).  All per-step inputs
are traced scalars — chaos epochs, overload windows, and recovery
interference never retrace the step.
"""

from __future__ import annotations

import time
from contextlib import nullcontext
from dataclasses import dataclass
from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.config import Config, global_config
from ..common.perf_counters import PerfCounters, PerfCountersBuilder, registry
from ..core.hashes import ceph_stable_mod, crush_hash32_2
from ..parallel.placement import shard_map
from ..recovery.peering import PeeringResult
from .histogram import (
    LAT_MIN_MS,
    N_BUCKETS,
    bucket_edges,
    bucketize,
    count_at_least,
    percentiles,
    scatter_hist,
)
from .qos import MClockArbiter

I32 = jnp.int32
U32 = jnp.uint32
F32 = jnp.float32

#: clip utilization below saturation so the M/D/1 delay stays finite
RHO_MAX = 0.97

_SALT2 = np.uint32(0x9E3779B9)  # decorrelates the read/write coin
_SALT3 = np.uint32(0x85EBCA6B)  # decorrelates the popularity-skew coin


@dataclass(frozen=True)
class TrafficMix:
    """A named client-workload shape, grounded in the arXiv:1709.05365
    characterization of online EC on large SSD arrays: a read/write
    split, a skewed object-popularity remap (``hot_permille`` of ops
    collapse onto a ``hot_objects``-wide hot set), and a bursty-arrival
    duty cycle (capacity headroom divides by ``burst_factor`` for
    ``burst_duty`` of every ``burst_period_s``).  The zero-valued
    defaults are the uniform workload — consumers gate each knob
    statically so a mix-less run traces today's exact graph."""

    name: str
    write_fraction: float = 0.25
    hot_permille: int = 0
    hot_objects: int = 64
    burst_period_s: float = 0.0
    burst_duty: float = 0.0
    burst_factor: float = 1.0


#: the named fleet workload mixes (selectable from ``config8_fleet``
#: and the CLI; the names pair with the same-named chaos scenarios)
TRAFFIC_MIXES = {
    m.name: m
    for m in (
        # steady-state online EC: read-mostly with a warm working set
        TrafficMix("ssd-steady", write_fraction=0.30,
                   hot_permille=400, hot_objects=256),
        # write-burst ingest: bursty arrivals on a write-heavy split
        TrafficMix("ssd-burst", write_fraction=0.45,
                   hot_permille=300, hot_objects=256,
                   burst_period_s=4.0, burst_duty=0.25,
                   burst_factor=3.0),
        # read-hot-spot serving: most ops collapse onto a small hot set
        TrafficMix("ssd-skew", write_fraction=0.10,
                   hot_permille=800, hot_objects=64),
    )
}


def resolve_mix(mix) -> TrafficMix | None:
    """``None`` | mix name | :class:`TrafficMix` -> the mix (or None)."""
    if mix is None or isinstance(mix, TrafficMix):
        return mix
    try:
        return TRAFFIC_MIXES[mix]
    except KeyError:
        raise ValueError(
            f"unknown traffic mix {mix!r}; known: "
            f"{sorted(TRAFFIC_MIXES)}"
        ) from None


def _skew_ids(ids, salt, hot_permille: int, hot_objects: int):
    """Skewed object popularity: ``hot_permille``/1000 of the op batch
    remaps onto the first ``hot_objects`` object ids (a seeded hash
    coin, decorrelated from the routing and read/write coins)."""
    coin = crush_hash32_2(ids, salt ^ _SALT3)
    hot = (coin % jnp.uint32(1000)).astype(I32) < jnp.int32(hot_permille)
    return jnp.where(hot, ids % jnp.uint32(hot_objects), ids)


def _traffic_reduce(
    mask, n_alive, acting_primary, ids, in_range, load_total,
    salt, pg_b, pg_bmask, k, size, min_size, write_permille,
    service_ms, cap_ops, rho_recovery, n_buckets, lat_min,
):
    """Outcome counts + histograms for one op batch, given the cluster-
    wide per-OSD load (psum'd by the sharded wrapper)."""
    pg, prim, is_write, blocked, degraded, _w = _route(
        mask, n_alive, acting_primary, ids, salt, pg_b, pg_bmask,
        k, size, min_size, write_permille,
    )
    ok = in_range & ~blocked
    rho = jnp.clip(
        load_total[prim] / jnp.maximum(cap_ops, jnp.float32(1e-6))
        + rho_recovery,
        0.0, RHO_MAX,
    )
    qd = rho / (1.0 - rho)
    amp = jnp.where(degraded & ~is_write, k.astype(F32), jnp.float32(1.0))
    lat = service_ms * amp * (1.0 + qd)
    okw = ok.astype(I32)
    counts = jnp.stack([
        jnp.sum(jnp.where(ok & ~degraded, 1, 0)),
        jnp.sum(jnp.where(ok & degraded, 1, 0)),
        jnp.sum(jnp.where(in_range & blocked, 1, 0)),
    ]).astype(I32)
    lat_hist = scatter_hist(
        bucketize(lat, n_buckets, lat_min), okw, n_buckets
    )
    qd_hist = scatter_hist(
        bucketize(qd, n_buckets, lat_min), okw, n_buckets
    )
    sums = jnp.stack([
        jnp.sum(jnp.where(ok, lat, 0.0)),
        jnp.sum(jnp.where(ok, qd, 0.0)),
    ]).astype(F32)
    max_rho = jnp.max(jnp.where(in_range, rho, 0.0)).astype(F32)
    # per-PG integrity feed: which PGs took a committed write (their
    # checksum rows must refresh: checksum-at-write) and which served
    # a degraded read (verify against the table before trusting the
    # reconstruct sources)
    n_pgs = mask.shape[0]
    written = jnp.zeros(n_pgs, I32).at[pg].add(
        jnp.where(ok & is_write, 1, 0)
    )
    deg_read = jnp.zeros(n_pgs, I32).at[pg].add(
        jnp.where(ok & degraded & ~is_write, 1, 0)
    )
    return counts, lat_hist, qd_hist, sums, max_rho, written, deg_read


def _route(
    mask, n_alive, acting_primary, ids, salt, pg_b, pg_bmask,
    k, size, min_size, write_permille,
):
    """Object ids -> (pg, primary, is_write, blocked, degraded, cost)."""
    h = crush_hash32_2(ids, salt)
    pg = ceph_stable_mod(h, pg_b, pg_bmask).astype(I32)
    coin = crush_hash32_2(h, salt ^ _SALT2)
    is_write = (coin % jnp.uint32(1000)).astype(I32) < write_permille
    nsurv = jax.lax.population_count(mask[pg]).astype(I32)
    alive = n_alive[pg]
    blocked = jnp.where(is_write, alive < min_size, nsurv < k)
    degraded = ~blocked & (nsurv < size)
    # primary-side op cost: a degraded read fans in k shard reads, a
    # write touches all size slots, a clean read is one unit
    cost = jnp.where(
        is_write, size, jnp.where(degraded, k, jnp.int32(1))
    ).astype(F32)
    return pg, acting_primary[pg], is_write, blocked, degraded, cost


def _scatter_load(
    mask, n_alive, acting_primary, ids, in_range,
    salt, pg_b, pg_bmask, k, size, min_size, write_permille, n_osds,
):
    """Per-OSD demand from this batch slice (blocked ops never load)."""
    _pg, prim, _w, blocked, _d, cost = _route(
        mask, n_alive, acting_primary, ids, salt, pg_b, pg_bmask,
        k, size, min_size, write_permille,
    )
    w = jnp.where(in_range & ~blocked, cost, 0.0)
    return jnp.zeros(n_osds, F32).at[prim].add(w)


def traffic_step(
    n_ops: int,
    n_osds: int,
    n_buckets: int = N_BUCKETS,
    lat_min: float = LAT_MIN_MS,
):
    """Single-device step: ``f(mask, n_alive, acting_primary, salt,
    pg_b, pg_bmask, k, size, min_size, write_permille, service_ms,
    cap_ops, rho_recovery) -> (counts [3], lat_hist, qd_hist,
    sums [2], max_rho, written [pg], deg_read [pg])``.  Everything but
    the shapes is traced."""

    def step(
        mask, n_alive, acting_primary, salt, pg_b, pg_bmask,
        k, size, min_size, write_permille,
        service_ms, cap_ops, rho_recovery,
    ):
        ids = jnp.arange(n_ops, dtype=U32)
        in_range = jnp.ones(n_ops, dtype=bool)
        load = _scatter_load(
            mask, n_alive, acting_primary, ids, in_range,
            salt, pg_b, pg_bmask, k, size, min_size, write_permille,
            n_osds,
        )
        return _traffic_reduce(
            mask, n_alive, acting_primary, ids, in_range, load,
            salt, pg_b, pg_bmask, k, size, min_size, write_permille,
            service_ms, cap_ops, rho_recovery, n_buckets, lat_min,
        )

    return jax.jit(step)


def sharded_traffic_step(
    mesh: Mesh,
    ops_per_device: int,
    n_osds: int,
    axis: str | None = None,
    n_buckets: int = N_BUCKETS,
    lat_min: float = LAT_MIN_MS,
):
    """Mesh step: each device generates its op-id slice from
    ``axis_index`` (no op-axis input to shard), the per-OSD load is
    psum'd *before* the queue model so every op sees the cluster-wide
    utilization, and counts/histograms/sums are psum'd so every device
    — and every rank under multihost — holds identical outputs.
    ``valid`` masks the padded id tail."""
    axis = axis or mesh.axis_names[0]

    def local(
        mask, n_alive, acting_primary, salt, pg_b, pg_bmask,
        k, size, min_size, write_permille,
        service_ms, cap_ops, rho_recovery, valid,
    ):
        start = jax.lax.axis_index(axis).astype(U32) * jnp.uint32(
            ops_per_device
        )
        ids = start + jnp.arange(ops_per_device, dtype=U32)
        in_range = ids.astype(I32) < valid
        load = jax.lax.psum(
            _scatter_load(
                mask, n_alive, acting_primary, ids, in_range,
                salt, pg_b, pg_bmask, k, size, min_size,
                write_permille, n_osds,
            ),
            axis,
        )
        (counts, lat_hist, qd_hist, sums, max_rho, written,
         deg_read) = _traffic_reduce(
            mask, n_alive, acting_primary, ids, in_range, load,
            salt, pg_b, pg_bmask, k, size, min_size, write_permille,
            service_ms, cap_ops, rho_recovery, n_buckets, lat_min,
        )
        return (
            jax.lax.psum(counts, axis),
            jax.lax.psum(lat_hist, axis),
            jax.lax.psum(qd_hist, axis),
            jax.lax.psum(sums, axis),
            jax.lax.pmax(max_rho, axis),
            jax.lax.psum(written, axis),
            jax.lax.psum(deg_read, axis),
        )

    n_in = 14
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=tuple(P() for _ in range(n_in)),
            out_specs=tuple(P() for _ in range(7)),
        )
    )


def dirty_fraction(series) -> float:
    """Fraction of a run's epochs whose map moved (peering re-ran) —
    the workload-side marker the dirty-set compaction ladder keys on:
    a low dirty fraction means most epochs skip peering entirely, and
    within the dirty epochs the compacted path touches only the PG
    bucket the flips reach.  Accepts any series with a per-epoch
    ``dirty`` lane (:class:`~ceph_tpu.recovery.superstep.EpochSeries`
    or one fleet lane of it); recorded by ``bench/config10_scale`` as
    the ``dirty_fraction`` metric that positions a workload against
    the compaction-roofline crossover in ``bench/PERF_MODEL.md``."""
    n = len(series)
    if not n:
        return 0.0
    return float(np.asarray(series.dirty, dtype=np.int64).sum()) / n


@dataclass
class TrafficSample:
    """One epoch's client-traffic telemetry (host-side)."""

    t: float
    epoch: int
    ops: int
    served: int
    degraded: int
    blocked: int
    p50_ms: float
    p95_ms: float
    p99_ms: float
    mean_ms: float
    qd_p50: float
    qd_p99: float
    slow_ops: int
    slow_fraction: float
    max_osd_utilization: float
    rho_recovery: float
    ops_per_sec: float  # virtual: completed ops / inter-sample dt
    ops_per_sec_wall: float  # device throughput of the step itself

    @property
    def completed(self) -> int:
        return self.served + self.degraded

    @property
    def served_fraction(self) -> float:
        return self.served / self.ops if self.ops else 1.0

    @property
    def degraded_fraction(self) -> float:
        return self.degraded / self.ops if self.ops else 0.0

    @property
    def blocked_fraction(self) -> float:
        return self.blocked / self.ops if self.ops else 0.0

    def to_dict(self) -> dict:
        return {
            "t": round(self.t, 9),
            "epoch": self.epoch,
            "ops": self.ops,
            "served": self.served,
            "degraded": self.degraded,
            "blocked": self.blocked,
            "served_fraction": round(self.served_fraction, 9),
            "degraded_fraction": round(self.degraded_fraction, 9),
            "blocked_fraction": round(self.blocked_fraction, 9),
            "p50_ms": round(self.p50_ms, 6),
            "p95_ms": round(self.p95_ms, 6),
            "p99_ms": round(self.p99_ms, 6),
            "mean_ms": round(self.mean_ms, 6),
            "qd_p50": round(self.qd_p50, 6),
            "qd_p99": round(self.qd_p99, 6),
            "slow_ops": self.slow_ops,
            "slow_fraction": round(self.slow_fraction, 9),
            "max_osd_utilization": round(self.max_osd_utilization, 6),
            "rho_recovery": round(self.rho_recovery, 6),
            "ops_per_sec": round(self.ops_per_sec, 3),
            "ops_per_sec_wall": round(self.ops_per_sec_wall, 3),
        }


def _build_counters(edges: np.ndarray) -> PerfCounters:
    return (
        PerfCountersBuilder("workload")
        .add_u64_counter("ops_served", "client ops served clean")
        .add_u64_counter("ops_degraded",
                         "client ops served from a degraded PG")
        .add_u64_counter("ops_blocked",
                         "client ops blocked on an inactive PG")
        .add_u64_counter("slow_ops",
                         "ops past the slow-op latency threshold")
        .add_gauge("p99_ms", "latest per-epoch p99 op latency (ms)")
        .add_gauge("max_osd_utilization",
                   "latest peak per-OSD utilization (rho)")
        .add_histogram("op_latency_ms",
                       "client op latency distribution (ms)",
                       [float(e) for e in edges[:-1]])
        .create_perf_counters()
    )


def workload_counters(edges: np.ndarray | None = None) -> PerfCounters:
    """The process-wide ``workload`` perf-counter component."""
    return registry().get("workload") or _build_counters(
        bucket_edges() if edges is None else edges
    )


class TrafficEngine:
    """Drive the traffic step per health sample and fold the results
    into the observability stack.

    One engine owns one compiled step (fixed ``ops_per_step`` batch, so
    chaos epochs and overload windows never retrace), the virtual
    clock, the latency ladder, and the cumulative totals.  Call
    :meth:`observe` with the live peering result at every health
    snapshot; the returned :class:`TrafficSample` is what
    :class:`~ceph_tpu.obs.timeline.HealthTimeline` attaches to its
    sample and the SLO layer grades.

    ``arbiter`` (an :class:`~ceph_tpu.workload.qos.MClockArbiter`)
    makes client traffic a first-class QoS citizen: each step's bytes
    are admitted through the ``client`` class before the device launch,
    sharing policy with recovery.  ``recovery_capacity_bps`` converts
    observed inter-sample repair bandwidth into the uniform recovery-
    utilization term; an arbiter that caps recovery bandwidth therefore
    visibly caps client tail latency.

    ``overload`` (set via :meth:`set_overload`) divides per-OSD
    capacity by ``factor`` inside a virtual-time window — the induced
    incident the slow-op SLO must grade OK -> WARN -> OK across.
    """

    def __init__(
        self,
        clock: Callable[[], float],
        n_osds: int,
        pg_num: int,
        k: int,
        size: int,
        min_size: int,
        *,
        ops_per_step: int = 65536,
        write_fraction: float | None = None,
        mix=None,
        service_ms: float = 0.5,
        osd_capacity_ops_per_s: float | None = None,
        recovery_capacity_bps: float | None = None,
        op_bytes: int = 4096,
        slow_ms: float | None = None,
        seed: int = 0,
        mesh: Mesh | None = None,
        axis: str | None = None,
        arbiter: MClockArbiter | None = None,
        journal=None,
        config: Config | None = None,
        n_buckets: int = N_BUCKETS,
        lat_min: float = LAT_MIN_MS,
        flags=None,
        scrubber=None,
        read_shard=None,
    ):
        cfg = config or global_config()
        self.clock = clock
        self.n_osds = int(n_osds)
        self.pg_num = int(pg_num)
        self.pg_bmask = (1 << max(int(pg_num) - 1, 1).bit_length()) - 1
        self.k = int(k)
        self.size = int(size)
        self.min_size = int(min_size)
        self.ops_per_step = int(ops_per_step)
        # a named mix supplies the default read/write split (the
        # engine's batch is otherwise uniform; the epoch superstep is
        # where the skew/burst knobs land)
        self.mix = resolve_mix(mix)
        if write_fraction is None:
            write_fraction = (
                self.mix.write_fraction if self.mix is not None else 0.25
            )
        self.write_permille = int(round(float(write_fraction) * 1000))
        self.service_ms = float(service_ms)
        # default capacity: 2x a uniform spread of one batch per second
        self.osd_capacity_ops_per_s = float(
            osd_capacity_ops_per_s
            if osd_capacity_ops_per_s is not None
            else 2.0 * self.ops_per_step / self.n_osds
        )
        self.recovery_capacity_bps = (
            float(recovery_capacity_bps)
            if recovery_capacity_bps is not None
            else 0.0
        )
        self.op_bytes = int(op_bytes)
        self.slow_ms = float(
            slow_ms if slow_ms is not None
            else float(cfg.get("osd_op_complaint_time")) * 1000.0
        )
        self.seed = int(seed)
        self.arbiter = arbiter
        self.journal = journal
        # degraded-mode gating + the checksum-at-write loop: with a
        # ClusterFlags set attached, `pause` stalls the whole batch
        # (an all-zero sample, no device step, no admission); with a
        # Scrubber + read_shard attached, written PGs refresh their
        # checksum rows and degraded reads verify before trusting
        # their reconstruct sources
        self.flags = flags
        self.scrubber = scrubber
        self.read_shard = read_shard
        #: per-step bound on PGs CRC'd inline (the write path samples
        #: its integrity work; a full sweep is the scrubber's job)
        self.integrity_max_pgs_per_step = 16
        self.paused_steps = 0
        self.writes_checksummed = 0
        self.degraded_reads_verified = 0
        self.read_verify_failures = 0
        self.n_buckets = int(n_buckets)
        self.lat_min = float(lat_min)
        self.edges = bucket_edges(self.n_buckets, self.lat_min)
        self.pc = workload_counters(self.edges)
        self.mesh = mesh
        if mesh is None:
            self._step = traffic_step(
                self.ops_per_step, self.n_osds, self.n_buckets,
                self.lat_min,
            )
            self.n_devices = 1
            self._ops_local = self.ops_per_step
        else:
            self.axis = axis or mesh.axis_names[0]
            self.n_devices = int(mesh.devices.size)
            self._ops_local = -(-self.ops_per_step // self.n_devices)
            self._step = sharded_traffic_step(
                mesh, self._ops_local, self.n_osds, self.axis,
                self.n_buckets, self.lat_min,
            )
        self._steps = 0
        self._last_t: float | None = None
        self._last_bytes = 0
        self._overload: tuple[float, float, float] | None = None
        # cumulative totals (the headline ops/s and the Prometheus
        # histogram are cluster-lifetime aggregates)
        self.total_ops = 0
        self.total_served = 0
        self.total_degraded = 0
        self.total_blocked = 0
        self.total_slow = 0
        self.total_wall_s = 0.0
        self._cum_lat_hist = np.zeros(self.n_buckets, np.int64)
        self._cum_lat_sum_ms = 0.0
        self.samples: list[TrafficSample] = []

    def set_overload(self, t0: float, t1: float, factor: float) -> None:
        """Divide per-OSD capacity by ``factor`` while virtual time is
        inside ``[t0, t1)`` (the induced-incident knob)."""
        self._overload = (float(t0), float(t1), float(factor))

    def _overload_factor(self, t: float) -> float:
        if self._overload is None:
            return 1.0
        t0, t1, f = self._overload
        return f if t0 <= t < t1 else 1.0

    def _put(self, host: np.ndarray):
        sharding = NamedSharding(self.mesh, P())
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx]
        )

    def observe(
        self,
        peering: PeeringResult,
        epoch: int | None = None,
        bytes_recovered: int = 0,
    ) -> TrafficSample:
        """Route one op batch against the current cluster state and
        fold it into the telemetry.  ``bytes_recovered`` is cumulative
        (the same figure the health timeline records) — the delta since
        the last observation becomes the recovery-utilization term."""
        if self.flags is not None and "pause" in self.flags:
            # the `pause` flag stalls all client IO: no admission, no
            # device step — the sample records a zero-op interval so
            # the series shows the outage instead of skipping it
            t = float(self.clock())
            ep = int(peering.epoch_cur if epoch is None else epoch)
            sample = TrafficSample(
                t=t, epoch=ep, ops=0, served=0, degraded=0, blocked=0,
                p50_ms=0.0, p95_ms=0.0, p99_ms=0.0, mean_ms=0.0,
                qd_p50=0.0, qd_p99=0.0, slow_ops=0, slow_fraction=0.0,
                max_osd_utilization=0.0, rho_recovery=0.0,
                ops_per_sec=0.0, ops_per_sec_wall=0.0,
            )
            self.paused_steps += 1
            self._last_t = t
            self._last_bytes = int(bytes_recovered)
            self.samples.append(sample)
            if self.journal is not None:
                self.journal.event("traffic.paused", epoch=ep, t=t)
            return sample
        if self.arbiter is not None:
            self.arbiter.request(
                "client", self.ops_per_step * self.op_bytes
            )
        t = float(self.clock())
        dt = (t - self._last_t) if self._last_t is not None else 0.0
        # the batch is modeled as arriving over the inter-sample
        # interval; floor it (and default the first, interval-less
        # sample to a nominal second) so back-to-back snapshots — a
        # revise landing right after a window — don't read a full
        # batch as an instantaneous demand spike
        dt_eff = max(dt, 0.25) if self._last_t is not None else 1.0
        rec_bps = max(bytes_recovered - self._last_bytes, 0) / dt_eff
        rho_recovery = (
            min(rec_bps / self.recovery_capacity_bps, 0.9)
            if self.recovery_capacity_bps > 0
            else 0.0
        )
        cap_ops = (
            self.osd_capacity_ops_per_s * dt_eff
            / self._overload_factor(t)
        )
        salt = np.uint32(
            (self.seed * 2654435761 + self._steps * 40503) & 0xFFFFFFFF
        )
        if self.mesh is None and peering.dev_survivor_mask is not None:
            # fused-pipeline peering: the router inputs are already
            # device-resident — feed them straight to the compiled step
            # instead of bouncing the [pg]-wide tables through the host
            mask_in = peering.dev_survivor_mask
            alive_in = peering.dev_n_alive
            prim_in = peering.dev_acting_primary
        else:
            mask_in = np.ascontiguousarray(peering.survivor_mask, np.uint32)
            alive_in = np.ascontiguousarray(peering.n_alive, np.int32)
            prim_in = np.ascontiguousarray(peering.acting_primary, np.int32)
        args = [
            mask_in,
            alive_in,
            prim_in,
            salt,
            np.uint32(self.pg_num),
            np.uint32(self.pg_bmask),
            np.int32(self.k),
            np.int32(self.size),
            np.int32(self.min_size),
            np.int32(self.write_permille),
            np.float32(self.service_ms),
            np.float32(cap_ops),
            np.float32(rho_recovery),
        ]
        if self.mesh is not None:
            args.append(np.int32(self.ops_per_step))
            args = [self._put(np.asarray(a)) for a in args]
        ep = int(peering.epoch_cur if epoch is None else epoch)
        with self._jspan("traffic.step", epoch=ep, ops=self.ops_per_step):
            # real wall rate for the step  # jaxlint: disable=J010
            t0 = time.perf_counter()
            (counts, lat_hist, qd_hist, sums, max_rho, written,
             deg_read) = self._step(*args)
            counts = np.asarray(counts)
            lat_hist = np.asarray(lat_hist)
            qd_hist = np.asarray(qd_hist)
            sums = np.asarray(sums)
            # measured step wall rate, reported next to simulated time
            # and never mixed into it  # jaxlint: disable=J010
            wall = time.perf_counter() - t0
        served, degraded, blocked = (int(c) for c in counts)
        ok = served + degraded
        p50, p95, p99 = percentiles(lat_hist, self.edges)
        qd_p50, _qd_p95, qd_p99 = percentiles(qd_hist, self.edges)
        slow = count_at_least(lat_hist, self.edges, self.slow_ms)
        sample = TrafficSample(
            t=t,
            epoch=ep,
            ops=self.ops_per_step,
            served=served,
            degraded=degraded,
            blocked=blocked,
            p50_ms=p50,
            p95_ms=p95,
            p99_ms=p99,
            mean_ms=float(sums[0]) / ok if ok else 0.0,
            qd_p50=qd_p50,
            qd_p99=qd_p99,
            slow_ops=slow,
            slow_fraction=slow / self.ops_per_step,
            max_osd_utilization=float(max_rho),
            rho_recovery=rho_recovery,
            ops_per_sec=ok / dt if dt > 0 else 0.0,
            ops_per_sec_wall=self.ops_per_step / wall if wall > 0 else 0.0,
        )
        self._steps += 1
        self._last_t = t
        self._last_bytes = int(bytes_recovered)
        self.total_ops += sample.ops
        self.total_served += served
        self.total_degraded += degraded
        self.total_blocked += blocked
        self.total_slow += slow
        self.total_wall_s += wall
        self._cum_lat_hist += lat_hist.astype(np.int64)
        self._cum_lat_sum_ms += float(sums[0])
        self.pc.inc("ops_served", served)
        self.pc.inc("ops_degraded", degraded)
        self.pc.inc("ops_blocked", blocked)
        self.pc.inc("slow_ops", slow)
        self.pc.set("p99_ms", p99)
        self.pc.set("max_osd_utilization", float(max_rho))
        self.pc.hset(
            "op_latency_ms",
            [int(c) for c in self._cum_lat_hist],
            self._cum_lat_sum_ms,
        )
        self.samples.append(sample)
        self._integrity(written, deg_read, peering, ep)
        return sample

    def _integrity(self, written, deg_read, peering, epoch: int) -> None:
        """The checksum-at-write loop (bluestore analog: checksum the
        data in flight, store it with the onode): PGs that took a
        committed write refresh their Scrubber checksum rows, and PGs
        that served a degraded read verify their surviving shards
        against the table before the reconstruct is trusted — rot can
        no longer hide between scrub passes."""
        if self.scrubber is None or self.read_shard is None:
            return
        lim = self.integrity_max_pgs_per_step
        wpgs = np.flatnonzero(np.asarray(written))[:lim]
        for pg in wpgs:
            self.scrubber.note_write(int(pg), self.read_shard)
        self.writes_checksummed += int(len(wpgs))
        rpgs = np.flatnonzero(np.asarray(deg_read))[:lim]
        for pg in rpgs:
            pg = int(pg)
            bad = self.scrubber.verify_read(
                pg, self.read_shard,
                mask=int(peering.survivor_mask[pg]),
            )
            self.degraded_reads_verified += 1
            if bad:
                self.read_verify_failures += 1
                if self.journal is not None:
                    self.journal.event(
                        "traffic.read_verify_failed",
                        epoch=epoch, pg=pg, shards=sorted(bad),
                    )

    def _jspan(self, name: str, **attrs):
        if self.journal is not None:
            return self.journal.span(name, **attrs)
        return nullcontext()

    @property
    def ops_per_sec_wall(self) -> float:
        """Lifetime device throughput: routed ops per wall second."""
        return self.total_ops / self.total_wall_s if self.total_wall_s else 0.0

    def summary(self) -> dict:
        """Cumulative totals (the bench JSON / client-io panel feed)."""
        total = self.total_ops or 1
        return {
            "steps": self._steps,
            "ops": self.total_ops,
            "served": self.total_served,
            "degraded": self.total_degraded,
            "blocked": self.total_blocked,
            "slow_ops": self.total_slow,
            "degraded_fraction": round(self.total_degraded / total, 9),
            "blocked_fraction": round(self.total_blocked / total, 9),
            "ops_per_sec_wall": round(self.ops_per_sec_wall, 3),
            "paused_steps": self.paused_steps,
            "writes_checksummed": self.writes_checksummed,
            "degraded_reads_verified": self.degraded_reads_verified,
            "read_verify_failures": self.read_verify_failures,
        }
