"""mclock-style QoS arbiter: client traffic and recovery share bandwidth.

The reference schedules OSD work with dmClock (``osd_op_queue =
mclock_scheduler``): every class gets a *reservation* (bytes/s it is
guaranteed), a *weight* (its share of whatever is left), and a *limit*
(a hard cap).  Here the arbiter replaces the executor's lone
:class:`~ceph_tpu.recovery.executor.TokenBucket` as the admission
gate: each request is tagged

- ``r_tag`` — the time the reservation schedule would serve it
  (``prev_r + nbytes / reservation``),
- ``p_tag`` — the proportional-share schedule
  (``prev_p + nbytes / (weight_share * capacity)``),
- ``l_tag`` — the limit schedule (``prev_l + nbytes / limit``),

and admitted at ``max(l_tag_prev, min(r_tag, p_tag))`` — served
immediately while inside its reservation, by weight once reservations
are met, never past its limit.  The serial simulator sleeps the
admission delay on the injectable clock, so chaos runs stay
deterministic and virtual-clocked.  (Full dmClock compares tags
*across* classes at a central queue; with one serial caller per class
the per-class tag schedule gives the same rate guarantees, which is
what the starvation tests assert.)
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable

from ..common.config import Config, global_config


@dataclass(frozen=True)
class QoSClass:
    """One traffic class's policy (the ``osd_mclock_scheduler_*_res/
    wgt/lim`` analog).  Rates are bytes/s; 0 disables that term
    (no reservation / no cap)."""

    name: str
    reservation: float = 0.0
    weight: float = 1.0
    limit: float = 0.0


@dataclass
class _ClassState:
    spec: QoSClass
    r_tag: float = 0.0
    p_tag: float = 0.0
    l_tag: float = 0.0
    granted_bytes: int = 0
    requests: int = 0
    waited_s: float = 0.0


class MClockArbiter:
    """Serial mclock admission over an injectable clock.

    ``capacity_bps`` anchors the proportional term: a class of weight
    ``w`` receives ``w / sum(weights)`` of it when every class is
    backlogged.  ``request(name, nbytes)`` blocks (via ``sleep``) until
    the class's schedule admits the bytes and returns the seconds
    waited.
    """

    def __init__(
        self,
        classes: list[QoSClass],
        capacity_bps: float,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if not classes:
            raise ValueError("MClockArbiter needs at least one QoSClass")
        self.capacity_bps = float(capacity_bps)
        self._clock = clock
        self._sleep = sleep
        self._classes: dict[str, _ClassState] = {
            c.name: _ClassState(c) for c in classes
        }
        total_w = sum(max(c.weight, 0.0) for c in classes) or 1.0
        self._share: dict[str, float] = {
            c.name: max(c.weight, 0.0) / total_w for c in classes
        }

    @classmethod
    def from_config(
        cls,
        capacity_bps: float,
        config: Config | None = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ) -> "MClockArbiter":
        """The standard client/recovery/scrub trio from the
        ``osd_mclock_*`` options (scrub is the background integrity
        class: it shares the same tag algebra, so a scrub storm admits
        by weight and can never starve the other two)."""
        cfg = config or global_config()
        return cls(
            [
                QoSClass(
                    "client",
                    reservation=float(cfg.get("osd_mclock_client_res_bps")),
                    weight=float(cfg.get("osd_mclock_client_wgt")),
                    limit=float(cfg.get("osd_mclock_client_lim_bps")),
                ),
                QoSClass(
                    "recovery",
                    reservation=float(cfg.get("osd_mclock_recovery_res_bps")),
                    weight=float(cfg.get("osd_mclock_recovery_wgt")),
                    limit=float(cfg.get("osd_mclock_recovery_lim_bps")),
                ),
                QoSClass(
                    "scrub",
                    reservation=float(cfg.get("osd_mclock_scrub_res_bps")),
                    weight=float(cfg.get("osd_mclock_scrub_wgt")),
                    limit=float(cfg.get("osd_mclock_scrub_lim_bps")),
                ),
            ],
            capacity_bps,
            clock=clock,
            sleep=sleep,
        )

    def request(self, name: str, nbytes: int) -> float:
        """Admit ``nbytes`` for class ``name``; returns seconds slept."""
        st = self._classes[name]
        spec = st.spec
        now = self._clock()
        # candidate start times under each schedule (an idle class's
        # stale tags snap forward to now — no banked credit)
        r_start = max(st.r_tag, now) if spec.reservation > 0 else None
        p_rate = self._share[name] * self.capacity_bps
        p_start = max(st.p_tag, now) if p_rate > 0 else now
        start = min(r_start, p_start) if r_start is not None else p_start
        if spec.limit > 0:
            start = max(start, max(st.l_tag, now))
        waited = 0.0
        if start > now:
            waited = start - now
            self._sleep(waited)
            st.waited_s += waited
            now = self._clock()
        # advance every tag by this grant
        if spec.reservation > 0:
            st.r_tag = max(st.r_tag, now) + nbytes / spec.reservation
        if p_rate > 0:
            st.p_tag = max(st.p_tag, now) + nbytes / p_rate
        if spec.limit > 0:
            st.l_tag = max(st.l_tag, now) + nbytes / spec.limit
        st.granted_bytes += int(nbytes)
        st.requests += 1
        return waited

    def granted(self, name: str) -> int:
        return self._classes[name].granted_bytes

    def waited(self, name: str) -> float:
        return self._classes[name].waited_s

    def summary(self) -> dict:
        """Per-class grant/wait telemetry (rides the bench JSON line)."""
        return {
            name: {
                "reservation_bps": st.spec.reservation,
                "weight": st.spec.weight,
                "limit_bps": st.spec.limit,
                "granted_bytes": st.granted_bytes,
                "requests": st.requests,
                "waited_s": round(st.waited_s, 6),
            }
            for name, st in sorted(self._classes.items())
        }
