"""Foreground client traffic: vmapped workload generation, per-op
outcome classification, device-resident latency percentiles, and the
mclock QoS arbiter that shares bandwidth between clients and recovery.

- :mod:`~ceph_tpu.workload.traffic` — the device traffic step (route
  via CRUSH hash -> classify from survivor bitmasks -> queue model ->
  log-bucket histograms, psum'd under a mesh) and the
  :class:`TrafficEngine` that drives it per health sample.
- :mod:`~ceph_tpu.workload.qos` — :class:`MClockArbiter`, the
  reservation/weight/limit admission gate (dmClock analog).
- :mod:`~ceph_tpu.workload.histogram` — the log2 bucket ladder and the
  host-side percentile merge.
- :mod:`~ceph_tpu.workload.writepath` — the online EC write path:
  the traffic step's committed writes drawn into fixed-shape batches
  and absorbed by the device-resident stripe buffer
  (:mod:`ceph_tpu.ec.online`) as a per-epoch encode stage inside the
  superstep scan.
"""

from .histogram import (
    LAT_MIN_MS,
    N_BUCKETS,
    bucket_edges,
    count_at_least,
    percentile,
    percentiles,
)
from .qos import MClockArbiter, QoSClass
from .traffic import (
    TRAFFIC_MIXES,
    TrafficEngine,
    TrafficMix,
    TrafficSample,
    resolve_mix,
    sharded_traffic_step,
    traffic_step,
    workload_counters,
)
from .writepath import (
    WritepathDriver,
    WritepathSeries,
    checkpointed_writepath,
    default_bitmatrix,
)

__all__ = [
    "LAT_MIN_MS",
    "MClockArbiter",
    "N_BUCKETS",
    "QoSClass",
    "TRAFFIC_MIXES",
    "TrafficEngine",
    "TrafficMix",
    "TrafficSample",
    "WritepathDriver",
    "WritepathSeries",
    "bucket_edges",
    "checkpointed_writepath",
    "default_bitmatrix",
    "count_at_least",
    "percentile",
    "percentiles",
    "resolve_mix",
    "sharded_traffic_step",
    "traffic_step",
    "workload_counters",
]
