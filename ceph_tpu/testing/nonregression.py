"""Non-regression archives: placements and EC encodings are ABI.

The reference pins bit-exact behavior with archived golden outputs
(``src/test/cli/crushtool/*.t`` recorded mappings and
``ceph_erasure_code_non_regression`` chunk archives): if an edit
changes any mapping or encoding, user data moves or becomes
unreadable.  Here the archive is a checked-in JSON of SHA-256 digests:
CRUSH mapping tables per (map shape, rule, tunables) and EC chunks per
(plugin, technique, k, m, packetsize), over fixed seeds.

Regenerate (only when a change is INTENTIONALLY breaking placement):
    python -m ceph_tpu.testing.nonregression > tests/golden/archive.json
"""

from __future__ import annotations

import hashlib
import json

import numpy as np


def _digest(arr: np.ndarray) -> str:
    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()


def crush_cases() -> dict[str, dict]:
    from ..models.clusters import build_flat, build_hierarchy
    from ..testing import cppref

    cases = {}
    specs = {
        "flat_16": build_flat(16),
        "flat_7_weighted": _weighted_flat(),
        "rack_host_osd": build_hierarchy([("rack", 2), ("host", 4)], 4),
    }
    for name, m in specs.items():
        rule = m.rule_by_name("replicated_rule")
        dense = m.to_dense()
        steps = [(s.op, s.arg1, s.arg2) for s in rule.steps]
        xs = np.arange(2048, dtype=np.uint32)
        w = np.full(dense.max_devices, 0x10000, np.uint32)
        res, lens = cppref.do_rule_batch(dense, steps, xs, w, 3)
        cases[name] = {
            "mappings_sha256": _digest(res),
            "lens_sha256": _digest(lens),
        }
    return cases


def _weighted_flat():
    from ..models.clusters import build_flat

    m = build_flat(7)
    root = m.bucket_by_name("default")
    for i, osd in enumerate(root.items):
        m.adjust_item_weight(root.id, osd, 0x8000 + i * 0x4000)
    return m


def ec_cases() -> dict[str, dict]:
    from ..ec import create

    rng = np.random.default_rng(0xCE9)
    obj = rng.integers(0, 256, 40_000, dtype=np.uint8)
    profiles = {
        "jerasure_rs_4_2": {"plugin": "jerasure", "technique": "reed_sol_van", "k": "4", "m": "2"},
        "jerasure_rs_8_3": {"plugin": "jerasure", "technique": "reed_sol_van", "k": "8", "m": "3"},
        "jerasure_r6_4_2": {"plugin": "jerasure", "technique": "reed_sol_r6_op", "k": "4", "m": "2"},
        "jerasure_cauchy_4_2_p8": {"plugin": "jerasure", "technique": "cauchy_good", "k": "4", "m": "2", "packetsize": "8"},
        "lrc_4_2_3": {"plugin": "lrc", "k": "4", "m": "2", "l": "3"},
        "shec_4_3_2": {"plugin": "shec", "k": "4", "m": "3", "c": "2"},
        "clay_4_2": {"plugin": "clay", "k": "4", "m": "2"},
        "clay_4_3_d5": {"plugin": "clay", "k": "4", "m": "3", "d": "5"},
        "clay_4_3_d4": {"plugin": "clay", "k": "4", "m": "3", "d": "4"},
        "jerasure_liberation_4_2_w7": {"plugin": "jerasure", "technique": "liberation", "k": "4", "m": "2", "w": "7", "packetsize": "8"},
        "jerasure_blaum_roth_4_2_w6": {"plugin": "jerasure", "technique": "blaum_roth", "k": "4", "m": "2", "w": "6", "packetsize": "8"},
        "jerasure_liber8tion_4_2": {"plugin": "jerasure", "technique": "liber8tion", "k": "4", "m": "2", "packetsize": "8"},
        "jerasure_rs_4_2_w16": {"plugin": "jerasure", "technique": "reed_sol_van", "k": "4", "m": "2", "w": "16"},
        "jerasure_rs_4_2_w32": {"plugin": "jerasure", "technique": "reed_sol_van", "k": "4", "m": "2", "w": "32"},
        "jerasure_cauchy_4_2_w16_p8": {"plugin": "jerasure", "technique": "cauchy_good", "k": "4", "m": "2", "w": "16", "packetsize": "8"},
    }
    out = {}
    for name, profile in profiles.items():
        ec = create(profile)
        n = ec.get_chunk_count()
        enc = ec.encode(set(range(n)), obj)
        out[name] = {
            "chunk_size": len(enc[0]),
            "chunks_sha256": {
                str(i): _digest(enc[i]) for i in sorted(enc)
            },
        }
    return out


def compile_once_cases() -> dict[str, dict]:
    """Runtime non-regression: the hot paths compile exactly once.

    Digests pin *what* the programs compute; this pins *how often they
    compile*.  Two second-invocation scenarios, both with value-only
    changes (weights / chunk bytes) that must not alter the program
    signature:

    - ``pool_mapping``: :class:`~ceph_tpu.osdmap.mapping.OSDMapMapping`
      ``.update()`` after a reweight — the whole-map remap loop of the
      upmap balancer and config3's timed region.
    - ``pattern_decode``: :class:`~ceph_tpu.recovery.executor
      .RecoveryExecutor` ``.run()`` on the same plan with fresh chunk
      data — config6's timed region.
    - ``schedule_decode``: the same second-run contract for a
      bitmatrix-native codec (liberation), whose pattern groups route
      through the cached XOR schedules of :mod:`ceph_tpu.ec.schedule`
      — the schedule cache plus the per-shape jit of the apply step
      must make repeated same-pattern decodes compile-free.
    - ``scrub_pass``: a second whole-pool CRC32C scrub
      (:class:`~ceph_tpu.recovery.scrub.Scrubber`) after a byte of the
      store rots — corruption changes values, never shapes, so the
      periodic background scrub must reuse the one compiled step.
    - ``heartbeat_tick``: the liveness detector's vmapped heartbeat
      update (:func:`ceph_tpu.recovery.liveness.heartbeat_step`) across
      suppression-mask, clock, and policy-knob changes — every knob is
      a traced scalar, so a whole chaos run of ticks is one compile.
    - ``fused_placement``: the single-launch placement→peering program
      (:mod:`ceph_tpu.recovery.pipeline`) across a down-OSD/reweight
      epoch — the chaos timeline's per-epoch cost must stay one cached
      executable, zero recompiles.
    - ``epoch_superstep``: the one-scan compiled epoch loop
      (:mod:`ceph_tpu.recovery.superstep`) over a chaos tape — a
      second same-shape epoch window must reuse the one compiled scan
      with ZERO device->host transfers inside it (the whole point of
      the superstep: host exits only at snapshot boundaries).
    - ``fleet_superstep``: the vmapped scenario-fleet scan
      (:mod:`ceph_tpu.recovery.fleet`) — growing the fleet within one
      power-of-two pad bucket (3 -> 4 clusters) must reuse the one
      compiled program with zero in-scan host transfers; fleet size is
      a value, never a shape.
    - ``compacted_superstep``: the dirty-set compaction ladder
      (``sparse_dirty_compaction``) — a chaos walk whose dirty-PG set
      grows 1 -> max crosses every power-of-two rung inside one scan;
      the warm rerun must hold ``CompileBudget(0)`` with zero in-scan
      host transfers under ``debug_bucket_checks`` (dirty-set size is
      the traced switch index, never a shape), and the compacted
      series must be bit-equal to the dense reference on the same
      walk.
    - ``online_write_batch``: the fused write-path scan
      (:mod:`ceph_tpu.workload.writepath`) — the per-epoch write cap
      is a traced scalar and the batch buffer is its power-of-two
      bucket, so varying write-batch sizes inside one bucket must
      reuse the one compiled scan (stripe lookups, LRU, parity deltas
      and all) with zero in-scan host transfers.
    - ``reconcile_round``: the divergent two-rank round
      (:mod:`ceph_tpu.recovery.reconcile`) — per-rank uniform-length
      chunk advances plus the one-launch ``merge_stacked`` join; a
      second same-length chunk + merge must reuse both executables
      with zero in-round host transfers (the per-round gather is the
      deliberate host seam, outside this region).
    - ``worksteal_dispatch``: the work-stealing dispatcher's drain
      loop (:mod:`ceph_tpu.recovery.dispatch`) — every sub-shard
      launch is zero-padded to one power-of-two piece bucket, so a
      second job with a DIFFERENT width (and sub-shard count) inside
      the same bucket must reuse the one per-device executable with
      zero in-window host transfers (``result()`` is the single
      deliberate host seam, outside this region).

    Raises ``AssertionError`` (from
    :func:`ceph_tpu.analysis.runtime_guard.assert_no_recompile`) if
    either second invocation triggers any XLA compile; returns the
    per-scenario compile counts observed during warm-up, for the
    report.
    """
    from ..analysis.runtime_guard import (
        CompileBudget,
        CompileCounter,
        assert_bucketed,
        assert_no_recompile,
    )
    from ..common.config import global_config
    from ..models.clusters import build_osdmap
    from ..osdmap.mapping import OSDMapMapping

    report: dict[str, dict] = {}

    # ---- compiled pool mapping: update -> reweight -> update ----
    m = build_osdmap(32, pg_num=16)
    mapping = OSDMapMapping(m)
    with CompileCounter() as warm:
        mapping.update()
    m.osd_weight[0] = 0x8000  # value-only edit: same program signature
    with assert_no_recompile("pool mapping second update"):
        mapping.update()
    report["pool_mapping"] = {
        "warm_compiles": warm.n_compiles, "second_compiles": 0,
    }

    # ---- pattern-grouped repair decode: run -> fresh data -> run ----
    from ..crush.map import ITEM_NONE as PEER_NONE
    from ..ec.backend import MatrixCodec
    from ..ec.gf import vandermonde_matrix
    from ..recovery import RecoveryExecutor, build_plan
    from ..recovery.peering import (
        PG_STATE_CLEAN,
        PG_STATE_DEGRADED,
        PeeringResult,
    )

    k, m_par, chunk = 4, 2, 128
    size = k + m_par
    masks = [0b001111, 0b110011]  # two erasure patterns -> two launches
    prev = np.arange(len(masks) * size, dtype=np.int32).reshape(-1, size)
    acting = prev.copy()
    flags = np.full(len(masks), PG_STATE_CLEAN, np.int32)
    mask_arr = np.full(len(masks), (1 << size) - 1, np.uint32)
    for i, mask in enumerate(masks):
        for s in range(size):
            if not (mask >> s) & 1:
                acting[i, s] = PEER_NONE
        flags[i] = PG_STATE_DEGRADED
        mask_arr[i] = mask
    peering = PeeringResult(
        pool_id=1, epoch_prev=1, epoch_cur=2, size=size, min_size=k,
        up=acting.copy(), up_primary=acting[:, 0].copy(),
        acting=acting, acting_primary=acting[:, 0].copy(),
        prev_acting=prev, flags=flags, survivor_mask=mask_arr,
        n_alive=(acting != PEER_NONE).sum(axis=1).astype(np.int32),
    )
    codec = MatrixCodec(vandermonde_matrix(k, m_par))
    plan = build_plan(peering, codec)

    def store_for(seed: int) -> dict[int, np.ndarray]:
        rng = np.random.default_rng(seed)
        out = {}
        for g in plan.groups:
            for pg in g.pgs:
                data = rng.integers(0, 256, (k, chunk), dtype=np.uint8)
                out[int(pg)] = np.vstack([data, codec.encode(data)])
        return out

    ex = RecoveryExecutor(codec)
    s1 = store_for(1)
    with CompileCounter() as warm:
        ex.run(plan, lambda pg, s: s1[pg][s])  # compiles per pattern
    s2 = store_for(2)  # fresh values, identical shapes
    with assert_no_recompile("pattern-grouped decode second run"):
        ex.run(plan, lambda pg, s: s2[pg][s])
    report["pattern_decode"] = {
        "warm_compiles": warm.n_compiles, "second_compiles": 0,
    }

    # ---- XOR-schedule decode: bit-level groups, same second-run bar ----
    from ..ec import gfw
    from ..ec.backend import BitmatrixCodec

    w, packetsize = 7, 8
    bcodec = BitmatrixCodec(gfw.liberation_bitmatrix(k, w), w, packetsize)
    chunk_b = 2 * w * packetsize
    masks_b = [0b011110, 0b111100]
    mask_arr_b = np.asarray(masks_b, np.uint32)
    peering_b = PeeringResult(
        pool_id=2, epoch_prev=1, epoch_cur=2, size=size, min_size=k,
        up=acting.copy(), up_primary=acting[:, 0].copy(),
        acting=acting, acting_primary=acting[:, 0].copy(),
        prev_acting=prev, flags=flags, survivor_mask=mask_arr_b,
        n_alive=(acting != PEER_NONE).sum(axis=1).astype(np.int32),
    )
    plan_b = build_plan(peering_b, bcodec)

    def store_for_b(seed: int) -> dict[int, np.ndarray]:
        rng = np.random.default_rng(seed)
        out = {}
        for g in plan_b.groups:
            for pg in g.pgs:
                data = rng.integers(0, 256, (k, chunk_b), dtype=np.uint8)
                out[int(pg)] = np.vstack([data, bcodec.encoder.encode(data)])
        return out

    ex_b = RecoveryExecutor(bcodec)
    b1 = store_for_b(1)
    with CompileCounter() as warm_b:
        ex_b.run(plan_b, lambda pg, s: b1[pg][s])  # compiles per pattern
    b2 = store_for_b(2)  # fresh values, identical shapes
    with assert_no_recompile("XOR-schedule decode second run"):
        ex_b.run(plan_b, lambda pg, s: b2[pg][s])
    report["schedule_decode"] = {
        "warm_compiles": warm_b.n_compiles, "second_compiles": 0,
    }

    # ---- CRC32C scrub: pass -> bit rot -> pass --------------------------
    from ..recovery.scrub import Scrubber, apply_bitrot

    n_pgs, n_shards, chunk_s = 8, size, 64
    rng = np.random.default_rng(3)
    store_s = {
        (pg, s): rng.integers(0, 256, chunk_s, dtype=np.uint8)
        for pg in range(n_pgs) for s in range(n_shards)
    }
    scrubber = Scrubber(n_pgs, n_shards)
    with CompileCounter() as warm_s:
        scrubber.build_checksums(lambda pg, s: store_s[(pg, s)])
        scrubber.scrub(lambda pg, s: store_s[(pg, s)])
    apply_bitrot(store_s[(3, 1)], 17, 0x40)  # value-only: same shapes
    with assert_no_recompile("scrub second pass"):
        sr = scrubber.scrub(lambda pg, s: store_s[(pg, s)])
    assert sr.n_inconsistent == 1, sr.n_inconsistent
    report["scrub_pass"] = {
        "warm_compiles": warm_s.n_compiles, "second_compiles": 0,
    }

    # ---- heartbeat tick: netsplit -> tick -> new masks/knobs -> tick ----
    from ..common.config import Config
    from ..recovery.chaos import VirtualClock
    from ..recovery.failure import parse_spec
    from ..recovery.liveness import LivenessDetector

    cfg = Config(env={})
    cfg.set("osd_heartbeat_grace", 1.0)
    cfg.set("mon_osd_min_down_reporters", 1)
    clock = VirtualClock()
    det = LivenessDetector(8, clock, config=cfg)
    # heartbeat_step is a module-level jit: anything else in this
    # process that ticked an 8-OSD detector already populated its
    # cache, which would serve the warm run silently (zero events)
    # and void the warm_compiles > 0 claim — start from a cold wrapper
    from ..recovery import liveness as _liveness

    _liveness.heartbeat_step.clear_cache()
    with CompileCounter() as warm_h:
        # warm both rare paths (tick step + the restore scatter) once
        det.apply(parse_spec("netsplit:5"))
        clock.advance(0.5)
        det.tick()
        det.apply(parse_spec("netsplit:5:restore"))
        clock.advance(0.1)
        det.tick()
    # value-only variations: different suppression masks, clock values,
    # knob values — all traced, so none may retrace anything
    det.apply(parse_spec("netsplit:1"))
    det.apply(parse_spec("netsplit:3"))
    cfg.set("osd_heartbeat_grace", 2.0)
    with assert_no_recompile("heartbeat tick value-only changes"):
        clock.advance(2.5)
        det.tick()
        det.apply(parse_spec("netsplit:1:restore"))
        clock.advance(2.0)
        det.tick()
    assert det.osds_down >= 1, det.summary()
    report["heartbeat_tick"] = {
        "warm_compiles": warm_h.n_compiles, "second_compiles": 0,
    }

    # ---- fused placement→peering: run -> down OSD -> run ---------------
    from ..osdmap.mapping import build_pool_state
    from ..recovery.peering import PeeringEngine

    m_f = build_osdmap(32, pg_num=16)
    eng = PeeringEngine(m_f, 1)
    state_a = build_pool_state(m_f, m_f.pools[1])
    with CompileCounter() as warm_p:
        eng.run(state_a, state_a)
    # value-only epoch change: an OSD drops, weights shift — every
    # changed bit is a traced input of the one fused program
    m_f.mark_down(3)
    m_f.osd_weight[5] = 0x8000
    state_b = build_pool_state(m_f, m_f.pools[1])
    with assert_no_recompile("fused placement second epoch"):
        eng.run(state_a, state_b)
    report["fused_placement"] = {
        "warm_compiles": warm_p.n_compiles, "second_compiles": 0,
    }

    # ---- epoch superstep: scan window -> same-shape window --------------
    from ..analysis.runtime_guard import track
    from ..recovery.chaos import ChaosEvent, ChaosTimeline
    from ..recovery.superstep import EpochDriver

    m_e = build_osdmap(32, pg_num=16, size=6, pool_kind="erasure")
    tape = ChaosTimeline([
        ChaosEvent(0.3, (parse_spec("osd:3:down_out"), parse_spec("slow:7"))),
    ])
    with CompileCounter() as warm_e:
        drv = EpochDriver(m_e, tape, n_ops=64)
        drv.run_superstep(8, pull=False)
    # a second same-shape window: the one scan executable is reused,
    # and with pull=False nothing inside it syncs to host — the
    # zero-host-transfer contract the staged path exists to contrast
    with assert_no_recompile("epoch superstep second window"):
        with track() as g_e:
            drv.run_superstep(8, pull=False)
    assert g_e.host_transfers == 0, g_e.host_transfers
    report["epoch_superstep"] = {
        "warm_compiles": warm_e.n_compiles, "second_compiles": 0,
        "in_scan_host_transfers": g_e.host_transfers,
    }

    # ---- fleet superstep: vmapped scan -> same pad bucket ---------------
    from ..recovery.fleet import FleetDriver, _pad_to

    fdrv = FleetDriver(m_e, seed=3, n_ops=64)
    tls_a = fdrv.sample(3, "ssd-burst")
    with CompileCounter() as warm_f:
        fdrv.run_fleet(8, tls_a, pull=False)
    # a fleet of 4 lands in the same power-of-two pad bucket as 3: the
    # one vmapped scan executable is reused, and with pull=False the
    # whole fleet window moves zero bytes to host.  The J013 runtime
    # twins audit the claim from both ends: the scenario asserts the
    # two fleets share a bucket, debug_bucket_checks makes the
    # stack_tapes seam re-check every pad it feeds the vmapped scan,
    # and CompileBudget(0) holds the warm rerun to zero XLA compiles.
    assert _pad_to(3) == _pad_to(4), (_pad_to(3), _pad_to(4))
    assert_bucketed("fleet superstep pad bucket", _pad_to(3), _pad_to(4))
    tls_b = fdrv.sample(4, "ssd-burst")
    cfg = global_config()
    prev_bucket = cfg.get("debug_bucket_checks")
    cfg.set("debug_bucket_checks", True)
    try:
        with CompileBudget(0, "fleet superstep same pad bucket"), \
                assert_no_recompile("fleet superstep same pad bucket"):
            with track() as g_f:
                fdrv.run_fleet(8, tls_b, pull=False)
    finally:
        cfg.set("debug_bucket_checks", prev_bucket)
    assert g_f.host_transfers == 0, g_f.host_transfers
    report["fleet_superstep"] = {
        "warm_compiles": warm_f.n_compiles, "second_compiles": 0,
        "in_scan_host_transfers": g_f.host_transfers,
    }

    # ---- compacted superstep: dirty-set size walk -> rerun --------------
    from ..common.config import Config

    m_c = build_osdmap(64, pg_num=128, size=6, pool_kind="erasure")
    cfg_c = Config(env={})
    cfg_c.set("sparse_dirty_compaction", "on")
    cfg_c.set("sparse_min_bucket", 4)
    cfg_c.set("debug_bucket_checks", True)
    # batches of 1, 2, 4, 8, 16 OSDs go down on successive epochs: the
    # dirty-PG set walks 1 -> max across every compaction-ladder rung
    # inside ONE compiled scan — dirty-set size must be a traced
    # VALUE (the switch index), never part of the program signature
    walk, start, batch, t = [], 0, 1, 0.3
    while start + batch <= 32:
        walk.append(ChaosEvent(t, tuple(
            parse_spec(f"osd:{i}") for i in range(start, start + batch)
        )))
        start += batch
        batch *= 2
        t += 0.5
    cdrv = EpochDriver(
        m_c, ChaosTimeline(walk), n_ops=64, config=cfg_c,
    )
    assert cdrv.compaction_enabled, "ladder empty with compaction on"
    for w in cdrv._dirty_ladder:
        assert_bucketed("compacted superstep ladder rung", w)
    with CompileCounter() as warm_c:
        series_c = cdrv.run_superstep(24)
    # the dense reference on the SAME walk: the ladder is an execution
    # strategy, never a different answer
    cfg_d = Config(env={})
    cfg_d.set("sparse_dirty_compaction", "off")
    ddrv = EpochDriver(
        m_c, ChaosTimeline(list(walk)), n_ops=64, config=cfg_d,
    )
    diff_c = series_c.diff(ddrv.run_superstep(24))
    assert not diff_c, f"compacted vs dense diverged: {diff_c}"
    prev_bucket = cfg.get("debug_bucket_checks")
    cfg.set("debug_bucket_checks", True)
    try:
        with CompileBudget(0, "compacted superstep dirty-set walk"), \
                assert_no_recompile("compacted superstep dirty-set walk"):
            with track() as g_c:
                cdrv.run_superstep(24, pull=False)
    finally:
        cfg.set("debug_bucket_checks", prev_bucket)
    assert g_c.host_transfers == 0, g_c.host_transfers
    report["compacted_superstep"] = {
        "warm_compiles": warm_c.n_compiles, "second_compiles": 0,
        "in_scan_host_transfers": g_c.host_transfers,
        "ladder": ",".join(str(w) for w in cdrv._dirty_ladder),
        "bitequal": not diff_c,
    }

    # ---- online write batch: scan -> smaller cap, same bucket ----------
    from ..workload.writepath import WritepathDriver

    wdrv = WritepathDriver(
        EpochDriver(m_e, tape, n_ops=64), n_sets=8, ways=2,
        max_writes=8,
    )
    with CompileCounter() as warm_w:
        wdrv.run_superstep(8, cap=5, pull=False)
    # a different write-batch size inside the same power-of-two bucket
    # (7 <= 8 slots) is a VALUE of the traced cap, never a shape: the
    # one fused scan — epoch pieces, stripe lookups, LRU maintenance,
    # vmapped parity-delta encode — is reused with zero in-scan host
    # transfers.  Same twin pairing as the fleet case: the batch
    # buffer's bucket is asserted power-of-two, the writepath's own
    # J013 seam re-checks under debug_bucket_checks, and
    # CompileBudget(0) enforces the zero-compile warm rerun.
    assert_bucketed("online write batch bucket", wdrv.batch_size)
    assert 7 <= wdrv.batch_size, wdrv.batch_size
    prev_bucket = cfg.get("debug_bucket_checks")
    cfg.set("debug_bucket_checks", True)
    try:
        with CompileBudget(0, "online write batch same bucket"), \
                assert_no_recompile("online write batch same bucket"):
            with track() as g_w:
                wdrv.run_superstep(8, cap=7, pull=False)
    finally:
        cfg.set("debug_bucket_checks", prev_bucket)
    assert g_w.host_transfers == 0, g_w.host_transfers
    report["online_write_batch"] = {
        "warm_compiles": warm_w.n_compiles, "second_compiles": 0,
        "in_scan_host_transfers": g_w.host_transfers,
    }

    # ---- reconcile round: 2-rank chunks -> merge -> same-shape chunks --
    from ..recovery.reconcile import DivergentDriver, merge_stacked

    tl_r = ChaosTimeline([
        ChaosEvent(0.3, (parse_spec("osd:5:down_out"),)),
        ChaosEvent(0.4, (parse_spec("rankdelay:1.40"),)),
    ])
    ddrv = DivergentDriver(m_e, tl_r, 2, n_ops=64)
    # same-shape merges elsewhere in the process would serve the warm
    # round from merge_stacked's cache and void the warm_compiles claim
    merge_stacked.clear_cache()
    with CompileCounter() as warm_r:
        for r in range(2):
            ddrv._advance(r, 8)
        ddrv._merge(ddrv._now_at(8))
    # a second uniform-length chunk per rank plus the merge: step
    # windows and skewed tapes are values, never shapes, so the one
    # scan and the one merge executable are reused — and nothing in
    # the round syncs to host (the per-round gather is the deliberate
    # host seam, outside this region)
    with assert_no_recompile("reconcile round second chunk"):
        with track() as g_r:
            for r in range(2):
                ddrv._advance(r, 16)
            ddrv._merge(ddrv._now_at(16))
    assert g_r.host_transfers == 0, g_r.host_transfers
    report["reconcile_round"] = {
        "warm_compiles": warm_r.n_compiles, "second_compiles": 0,
        "in_round_host_transfers": g_r.host_transfers,
    }

    # ---- work-stealing dispatch: drain -> same piece bucket -> drain ----
    import jax

    from ..ec.backend import TableEncoder
    from ..ec.gf import matrix_encode
    from ..recovery.dispatch import WorkStealingDispatcher, _next_pow2

    wenc = TableEncoder(vandermonde_matrix(k, m_par))
    disp = WorkStealingDispatcher(list(jax.devices()))
    denom = len(disp.chips) * disp.subshards_per_chip
    w_a, w_b = 3000, 4000  # different widths AND sub-shard counts...
    piece_a = _next_pow2(-(-w_a // denom))
    piece_b = _next_pow2(-(-w_b // denom))
    # ...but one power-of-two piece bucket: every launch is [k, piece]
    assert_bucketed("worksteal piece bucket", piece_a, piece_b)
    assert piece_a == piece_b, (piece_a, piece_b)
    rng_w = np.random.default_rng(11)
    src_a = rng_w.integers(0, 256, (k, w_a), dtype=np.uint8)
    src_b = rng_w.integers(0, 256, (k, w_b), dtype=np.uint8)
    with CompileCounter() as warm_d:
        job_a = disp.submit(wenc, src_a)
        disp.drain()
        np.testing.assert_array_equal(
            disp.result(job_a), matrix_encode(wenc.matrix, src_a)
        )
    with CompileBudget(0, "worksteal same piece bucket"), \
            assert_no_recompile("worksteal same piece bucket"):
        with track() as g_d:
            job_b = disp.submit(wenc, src_b)
            disp.drain()
    assert g_d.host_transfers == 0, g_d.host_transfers
    np.testing.assert_array_equal(
        disp.result(job_b), matrix_encode(wenc.matrix, src_b)
    )
    report["worksteal_dispatch"] = {
        "warm_compiles": warm_d.n_compiles, "second_compiles": 0,
        "in_window_host_transfers": g_d.host_transfers,
    }
    return report


def generate() -> dict:
    return {"version": 1, "crush": crush_cases(), "ec": ec_cases()}


if __name__ == "__main__":
    print(json.dumps(generate(), indent=1, sort_keys=True))
