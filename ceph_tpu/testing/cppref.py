"""ctypes bindings for the C++ CPU reference tier (cpp/).

Auto-builds ``cpp/build/lib{crushref,gfref}.so`` with make on first use.
The C++ tier is the repo's ground truth for CRUSH and GF semantics and
the single-core CPU baseline the TPU benchmarks compare against.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from functools import lru_cache

import numpy as np

_CPP_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "cpp"
)


def _build() -> str:
    build_dir = os.path.join(_CPP_DIR, "build")
    srcs = [os.path.join(_CPP_DIR, f) for f in ("crush_ref.cpp", "gf_ref.cpp", "Makefile")]
    libs = [os.path.join(build_dir, f) for f in ("libcrushref.so", "libgfref.so")]
    if not all(os.path.exists(p) for p in libs) or any(
        os.path.getmtime(s) > min(os.path.getmtime(p) for p in libs) for s in srcs
    ):
        subprocess.run(["make", "-C", _CPP_DIR], check=True, capture_output=True)
    return build_dir


class _CMapSpec(ctypes.Structure):
    _fields_ = [
        ("n_buckets", ctypes.c_int32),
        ("max_fanout", ctypes.c_int32),
        ("max_devices", ctypes.c_int32),
        ("choose_total_tries", ctypes.c_int32),
        ("choose_local_tries", ctypes.c_int32),
        ("choose_local_fallback_tries", ctypes.c_int32),
        ("chooseleaf_descend_once", ctypes.c_int32),
        ("chooseleaf_vary_r", ctypes.c_int32),
        ("chooseleaf_stable", ctypes.c_int32),
        ("alg", ctypes.POINTER(ctypes.c_int32)),
        ("type", ctypes.POINTER(ctypes.c_int32)),
        ("size", ctypes.POINTER(ctypes.c_int32)),
        ("items", ctypes.POINTER(ctypes.c_int32)),
        ("weights", ctypes.POINTER(ctypes.c_uint32)),
        ("scaled", ctypes.POINTER(ctypes.c_uint32)),
        ("tree_weights", ctypes.POINTER(ctypes.c_uint32)),
        ("max_tree_nodes", ctypes.c_int32),
    ]


class _CRuleStep(ctypes.Structure):
    _fields_ = [
        ("op", ctypes.c_int32),
        ("arg1", ctypes.c_int32),
        ("arg2", ctypes.c_int32),
    ]


ITEM_NONE = 0x7FFFFFFF


@lru_cache(maxsize=1)
def _libs():
    build_dir = _build()
    crush = ctypes.CDLL(os.path.join(build_dir, "libcrushref.so"))
    gf = ctypes.CDLL(os.path.join(build_dir, "libgfref.so"))

    crush.ct_hash2.restype = ctypes.c_uint32
    crush.ct_hash2.argtypes = [ctypes.c_uint32, ctypes.c_uint32]
    crush.ct_hash3.restype = ctypes.c_uint32
    crush.ct_hash3.argtypes = [ctypes.c_uint32] * 3
    crush.ct_crush_ln.restype = ctypes.c_uint64
    crush.ct_crush_ln.argtypes = [ctypes.c_uint32]
    crush.ct_str_hash_rjenkins.restype = ctypes.c_uint32
    crush.ct_str_hash_rjenkins.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
    crush.ct_do_rule_batch.restype = None
    crush.ct_hash4.restype = ctypes.c_uint32
    crush.ct_hash4.argtypes = [ctypes.c_uint32] * 4
    crush.ct_bucket_choose.restype = ctypes.c_int32
    gf.gfref_mul.restype = ctypes.c_uint8
    gf.gfref_mul.argtypes = [ctypes.c_uint8, ctypes.c_uint8]
    return crush, gf


def hash2(a: int, b: int) -> int:
    return _libs()[0].ct_hash2(a & 0xFFFFFFFF, b & 0xFFFFFFFF)


def hash3(a: int, b: int, c: int) -> int:
    return _libs()[0].ct_hash3(a & 0xFFFFFFFF, b & 0xFFFFFFFF, c & 0xFFFFFFFF)


def crush_ln(x: int) -> int:
    return _libs()[0].ct_crush_ln(x)


def str_hash_rjenkins(data: bytes) -> int:
    return _libs()[0].ct_str_hash_rjenkins(data, len(data))


def _as_ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def _make_spec(dense):
    """(_CMapSpec, keepalive-arrays) for a DenseCrushMap."""
    alg = np.ascontiguousarray(dense.alg, np.int32)
    btype = np.ascontiguousarray(dense.btype, np.int32)
    size = np.ascontiguousarray(dense.size, np.int32)
    items = np.ascontiguousarray(dense.items, np.int32)
    weights = np.ascontiguousarray(dense.weights, np.uint32)
    keep = [alg, btype, size, items, weights]
    scaled_p = tree_p = None
    if getattr(dense, "scaled", None) is not None:
        scaled = np.ascontiguousarray(dense.scaled, np.uint32)
        keep.append(scaled)
        scaled_p = _as_ptr(scaled, ctypes.c_uint32)
    if getattr(dense, "tree_weights", None) is not None:
        tree_w = np.ascontiguousarray(dense.tree_weights, np.uint32)
        keep.append(tree_w)
        tree_p = _as_ptr(tree_w, ctypes.c_uint32)
    spec = _CMapSpec(
        n_buckets=dense.n_buckets,
        max_fanout=dense.max_fanout,
        max_devices=dense.max_devices,
        choose_total_tries=dense.tunables.choose_total_tries,
        choose_local_tries=dense.tunables.choose_local_tries,
        choose_local_fallback_tries=dense.tunables.choose_local_fallback_tries,
        chooseleaf_descend_once=dense.tunables.chooseleaf_descend_once,
        chooseleaf_vary_r=dense.tunables.chooseleaf_vary_r,
        chooseleaf_stable=dense.tunables.chooseleaf_stable,
        alg=_as_ptr(alg, ctypes.c_int32),
        type=_as_ptr(btype, ctypes.c_int32),
        size=_as_ptr(size, ctypes.c_int32),
        items=_as_ptr(items, ctypes.c_int32),
        weights=_as_ptr(weights, ctypes.c_uint32),
        scaled=scaled_p,
        tree_weights=tree_p,
        max_tree_nodes=getattr(dense, "max_tree_nodes", 0),
    )
    return spec, keep


def bucket_choose(dense, bucket_idx: int, x: int, r: int) -> int:
    """Single legacy/modern bucket choose on the C++ tier (for
    differential tests against the Python oracle)."""
    crush, _ = _libs()
    spec, _keep = _make_spec(dense)
    return crush.ct_bucket_choose(
        ctypes.byref(spec), ctypes.c_int32(bucket_idx),
        ctypes.c_uint32(x & 0xFFFFFFFF), ctypes.c_int32(r)
    )


def hash4(a: int, b: int, c: int, d: int) -> int:
    return _libs()[0].ct_hash4(
        a & 0xFFFFFFFF, b & 0xFFFFFFFF, c & 0xFFFFFFFF, d & 0xFFFFFFFF
    )


def reset_retry_stats() -> None:
    _libs()[0].ct_reset_stats()


def retry_histogram() -> np.ndarray:
    """[64] int64 histogram of top-level failure counts per slot since
    the last reset (last bucket clamps) — crushtool --show-choose-tries
    data."""
    hist = np.zeros(64, np.int64)
    _libs()[0].ct_get_try_hist(_as_ptr(hist, ctypes.c_int64))
    return hist


def retry_stats() -> tuple[int, float, int]:
    """(max_ftotal, mean_ftotal, slots) accumulated since the last
    reset.  Counts top-level FAILURE rounds only (leaf sub-descents
    excluded; indep normalized to the same unit), so max_ftotal + 1
    bounds the batch engine's masked whole-batch retry-round
    (lax.while_loop trip) count for the same inputs — the number
    bench/PERF_MODEL.md's suspect 4 asks for."""
    crush, _ = _libs()
    mx = ctypes.c_int32()
    sm = ctypes.c_int64()
    n = ctypes.c_int64()
    crush.ct_get_stats(ctypes.byref(mx), ctypes.byref(sm), ctypes.byref(n))
    slots = max(int(n.value), 1)
    return int(mx.value), float(sm.value) / slots, int(n.value)


def do_rule_batch(
    dense,  # ceph_tpu.crush.map.DenseCrushMap
    steps: list[tuple[int, int, int]],
    xs: np.ndarray,
    osd_weight: np.ndarray,
    result_max: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Run a rule for every x on the C++ reference; returns (results, lens).

    results is int32 [n_x, result_max], padded with ITEM_NONE.
    """
    crush, _ = _libs()
    spec, _keep = _make_spec(dense)
    csteps = (_CRuleStep * len(steps))(*[_CRuleStep(*s) for s in steps])
    if result_max > 256:
        raise ValueError(
            f"result_max={result_max} exceeds the C++ reference's scratch "
            "cap of 256 (ct_do_rule_batch would silently no-op)"
        )
    xs = np.ascontiguousarray(xs, np.uint32)
    osd_weight = np.ascontiguousarray(osd_weight, np.uint32)
    n = len(xs)
    results = np.full((n, result_max), ITEM_NONE, np.int32)
    lens = np.zeros(n, np.int32)
    crush.ct_do_rule_batch(
        ctypes.byref(spec),
        csteps,
        ctypes.c_int32(len(steps)),
        _as_ptr(xs, ctypes.c_uint32),
        ctypes.c_int64(n),
        _as_ptr(osd_weight, ctypes.c_uint32),
        ctypes.c_int32(len(osd_weight)),
        _as_ptr(results, ctypes.c_int32),
        _as_ptr(lens, ctypes.c_int32),
        ctypes.c_int32(result_max),
    )
    return results, lens


# ---- GF reference wrappers ----


def gf_tables() -> tuple[np.ndarray, np.ndarray]:
    _, gf = _libs()
    log = np.zeros(256, np.uint8)
    exp = np.zeros(256, np.uint8)
    gf.gfref_tables(_as_ptr(log, ctypes.c_uint8), _as_ptr(exp, ctypes.c_uint8))
    return log, exp


def gf_mul(a: int, b: int) -> int:
    return _libs()[1].gfref_mul(a, b)


def vandermonde_matrix(k: int, m: int) -> np.ndarray:
    _, gf = _libs()
    out = np.zeros((m, k), np.uint8)
    rc = gf.gfref_vandermonde_matrix(k, m, _as_ptr(out, ctypes.c_uint8))
    if rc != 0:
        raise ValueError(f"vandermonde_matrix({k},{m}) failed rc={rc}")
    return out


def raid6_matrix(k: int) -> np.ndarray:
    _, gf = _libs()
    out = np.zeros((2, k), np.uint8)
    gf.gfref_raid6_matrix(k, _as_ptr(out, ctypes.c_uint8))
    return out


def cauchy_matrix(k: int, m: int) -> np.ndarray:
    _, gf = _libs()
    out = np.zeros((m, k), np.uint8)
    rc = gf.gfref_cauchy_matrix(k, m, _as_ptr(out, ctypes.c_uint8))
    if rc != 0:
        raise ValueError(f"cauchy_matrix({k},{m}) failed rc={rc}")
    return out


def matrix_encode(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """data: [k, size] uint8 -> coding [m, size] uint8."""
    _, gf = _libs()
    m, k = matrix.shape
    data = np.ascontiguousarray(data, np.uint8)
    assert data.shape[0] == k
    size = data.shape[1]
    coding = np.zeros((m, size), np.uint8)
    gf.gfref_matrix_encode_flat(
        k,
        m,
        _as_ptr(np.ascontiguousarray(matrix, np.uint8), ctypes.c_uint8),
        _as_ptr(data, ctypes.c_uint8),
        _as_ptr(coding, ctypes.c_uint8),
        ctypes.c_int64(size),
    )
    return coding


def invert_matrix(mat: np.ndarray) -> np.ndarray:
    _, gf = _libs()
    k = mat.shape[0]
    inv = np.zeros((k, k), np.uint8)
    rc = gf.gfref_invert_matrix(
        k,
        _as_ptr(np.ascontiguousarray(mat, np.uint8), ctypes.c_uint8),
        _as_ptr(inv, ctypes.c_uint8),
    )
    if rc != 0:
        raise ValueError("singular matrix")
    return inv


def matrix_to_bitmatrix(matrix: np.ndarray) -> np.ndarray:
    _, gf = _libs()
    m, k = matrix.shape
    out = np.zeros((m * 8, k * 8), np.uint8)
    gf.gfref_matrix_to_bitmatrix(
        k,
        m,
        _as_ptr(np.ascontiguousarray(matrix, np.uint8), ctypes.c_uint8),
        _as_ptr(out, ctypes.c_uint8),
    )
    return out


def bitmatrix_encode(
    bitmatrix: np.ndarray, data: np.ndarray, packetsize: int
) -> np.ndarray:
    """data: [k, size] -> coding [m, size] with packet-interleave layout."""
    _, gf = _libs()
    mw, kw = bitmatrix.shape
    k, m = kw // 8, mw // 8
    data = np.ascontiguousarray(data, np.uint8)
    size = data.shape[1]
    assert size % (8 * packetsize) == 0
    coding = np.zeros((m, size), np.uint8)
    gf.gfref_bitmatrix_encode(
        k,
        m,
        _as_ptr(np.ascontiguousarray(bitmatrix, np.uint8), ctypes.c_uint8),
        _as_ptr(data, ctypes.c_uint8),
        _as_ptr(coding, ctypes.c_uint8),
        ctypes.c_int64(size),
        ctypes.c_int64(packetsize),
    )
    return coding


def invert_bitmatrix(mat: np.ndarray) -> np.ndarray:
    _, gf = _libs()
    n = mat.shape[0]
    inv = np.zeros((n, n), np.uint8)
    rc = gf.gfref_invert_bitmatrix(
        n,
        _as_ptr(np.ascontiguousarray(mat, np.uint8), ctypes.c_uint8),
        _as_ptr(inv, ctypes.c_uint8),
    )
    if rc != 0:
        raise ValueError("singular bitmatrix")
    return inv
