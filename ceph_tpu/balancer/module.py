"""Balancer module: evaluation + optimize/execute loop.

Parity with the reference's mgr balancer
(``src/pybind/mgr/balancer/module.py`` :: ``Module.serve`` /
``Eval`` / ``optimize`` / ``do_upmap`` / ``execute``), minus the mgr
daemon plumbing: the caller owns the tick loop; ``optimize`` returns a
plan (an Incremental), ``execute`` commits it as a new epoch.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..osdmap.map import Incremental, OSDMap
from ..osdmap.mapping import OSDMapMapping
from .crush_compat import do_crush_compat
from .upmap import calc_pg_upmaps, crush_device_weights, expected_pg_share


@dataclass
class Eval:
    """Distribution quality of a map (reference balancer ``Eval``)."""

    pool_scores: dict[int, float] = field(default_factory=dict)
    pool_stddev: dict[int, float] = field(default_factory=dict)
    pool_max_deviation: dict[int, float] = field(default_factory=dict)

    @property
    def score(self) -> float:
        """0 = perfectly balanced; higher = worse."""
        if not self.pool_scores:
            return 0.0
        return float(np.mean(list(self.pool_scores.values())))


class Balancer:
    def __init__(
        self,
        osdmap: OSDMap,
        mode: str = "upmap",
        max_deviation: float = 1.0,
        max_optimizations: int = 100,
    ):
        if mode not in ("upmap", "crush-compat"):
            raise ValueError(
                f"mode {mode!r} not supported (upmap / crush-compat)"
            )
        self.osdmap = osdmap
        self.mode = mode
        self.max_deviation = max_deviation
        self.max_optimizations = max_optimizations
        self.mapping = OSDMapMapping(osdmap)

    def evaluate(self, pools: list[int] | None = None) -> Eval:
        ev = Eval()
        n_osd = max(self.osdmap.max_osd, 1)
        for pool_id in pools or sorted(self.osdmap.pools):
            pool = self.osdmap.pools[pool_id]
            self.mapping.update(pool_id)
            counts = self.mapping.pg_counts_by_osd(pool_id, acting=False)
            expect = expected_pg_share(self.osdmap, pool, n_osd)
            if expect is None:
                continue
            cw = crush_device_weights(
                self.osdmap.crush, pool.crush_rule, n_osd
            )
            cw *= np.asarray(self.osdmap.osd_weight, np.float64)[:n_osd] / 0x10000
            active = cw > 0
            dev = counts[active] - expect[active]
            ev.pool_stddev[pool_id] = float(dev.std())
            ev.pool_max_deviation[pool_id] = float(np.abs(dev).max())
            # reference-style score: normalized sum of squared deviation
            denom = max(expect[active].sum(), 1.0)
            ev.pool_scores[pool_id] = float((dev**2).sum() / denom)
        return ev

    def optimize(self, pools: list[int] | None = None) -> Incremental:
        """One balancing step (upmap mode); empty Incremental means
        balanced."""
        if self.mode != "upmap":
            raise ValueError("optimize() returns a plan only in upmap "
                             "mode; use tick() for crush-compat")
        return calc_pg_upmaps(
            self.osdmap,
            max_deviation=self.max_deviation,
            max_entries=self.max_optimizations,
            pools=pools,
            mapping=self.mapping,
        )

    def execute(self, plan: Incremental) -> bool:
        """Commit the plan as a new epoch; False if it was empty."""
        if not (plan.new_pg_upmap_items or plan.old_pg_upmap_items
                or plan.new_pg_upmap or plan.old_pg_upmap):
            return False
        self.osdmap.apply_incremental(plan)
        return True

    def tick(self, pools: list[int] | None = None) -> bool:
        """One serve-loop iteration: optimize + execute.

        upmap mode emits pg_upmap_items through an Incremental;
        crush-compat mode descends the compat choose_args weight set
        (placement consumes it directly) and bumps the epoch when it
        changed — the reference commits the same two ways
        (``do_upmap`` vs ``do_crush_compat``).
        """
        if self.mode == "crush-compat":
            changed = do_crush_compat(
                self.osdmap,
                pools=pools,
                max_deviation=self.max_deviation,
                mapping=self.mapping,
            )
            if changed:
                self.osdmap.epoch += 1
            return changed
        return self.execute(self.optimize(pools))
