"""pg_num autoscaler (mgr pg_autoscaler module analog).

Parity with the reference's ``src/pybind/mgr/pg_autoscaler/module.py``
sizing policy: each pool's target PG count is

    pgs = target_pgs_per_osd * osd_count * capacity_ratio / pool_size

rounded to the nearest power of two, clamped to bounds, and only
*applied* when the current pg_num is off by more than a 3x threshold
(to avoid churn), since splitting/merging moves data.  Capacity ratio
comes from pool ``target_size_ratio`` (explicit shares) or defaults to
an equal split among pools under the same CRUSH root.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..osdmap.map import OSDMap, Pool

DEFAULT_TARGET_PGS_PER_OSD = 100
THRESHOLD = 3.0


def _nearest_power_of_two(n: float) -> int:
    if n <= 1:
        return 1
    lo = 1 << (int(n).bit_length() - 1)
    hi = lo << 1
    return lo if (n - lo) < (hi - n) else hi


@dataclass
class Recommendation:
    pool_id: int
    current_pg_num: int
    target_pg_num: int
    capacity_ratio: float
    would_adjust: bool

    @property
    def final_pg_num(self) -> int:
        return self.target_pg_num if self.would_adjust else self.current_pg_num


class PgAutoscaler:
    def __init__(
        self,
        osdmap: OSDMap,
        target_pgs_per_osd: int = DEFAULT_TARGET_PGS_PER_OSD,
        threshold: float = THRESHOLD,
    ):
        self.osdmap = osdmap
        self.target_pgs_per_osd = target_pgs_per_osd
        self.threshold = max(threshold, 1.0)
        self.target_size_ratio: dict[int, float] = {}

    def set_target_size_ratio(self, pool_id: int, ratio: float) -> None:
        self.target_size_ratio[pool_id] = ratio

    def _capacity_ratios(self) -> dict[int, float]:
        pools = self.osdmap.pools
        explicit = {
            pid: self.target_size_ratio[pid]
            for pid in pools
            if pid in self.target_size_ratio
        }
        total_explicit = sum(explicit.values())
        rest = [pid for pid in pools if pid not in explicit]
        out = dict(explicit)
        if rest:
            remaining = max(0.0, 1.0 - min(total_explicit, 1.0))
            for pid in rest:
                out[pid] = remaining / len(rest)
        if total_explicit > 1.0:  # normalize over-subscription
            out = {pid: r / total_explicit for pid, r in out.items()}
        return out

    def recommend(self) -> list[Recommendation]:
        n_in = sum(
            1 for o in range(self.osdmap.max_osd) if not self.osdmap.is_out(o)
        )
        ratios = self._capacity_ratios()
        recs = []
        for pid, pool in sorted(self.osdmap.pools.items()):
            ratio = ratios.get(pid, 0.0)
            raw = (
                self.target_pgs_per_osd * max(n_in, 1) * ratio / max(pool.size, 1)
            )
            target = _nearest_power_of_two(raw)
            cur = pool.pg_num
            would = (
                cur * self.threshold < target or target * self.threshold < cur
            )
            recs.append(
                Recommendation(
                    pool_id=pid,
                    current_pg_num=cur,
                    target_pg_num=target,
                    capacity_ratio=ratio,
                    would_adjust=would,
                )
            )
        return recs

    def apply(self) -> bool:
        """Commit adjustments as a new epoch; True if anything changed."""
        recs = [r for r in self.recommend() if r.would_adjust]
        if not recs:
            return False
        from copy import deepcopy

        from ..osdmap.map import Incremental

        inc = Incremental(epoch=self.osdmap.epoch + 1)
        for r in recs:
            pool = deepcopy(self.osdmap.pools[r.pool_id])
            pool.pg_num = r.target_pg_num
            pool.pgp_num = r.target_pg_num
            inc.new_pools[pool.id] = pool
        self.osdmap.apply_incremental(inc)
        return True
