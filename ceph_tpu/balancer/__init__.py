from .upmap import calc_pg_upmaps
from .module import Balancer, Eval

__all__ = ["calc_pg_upmaps", "Balancer", "Eval"]
