"""crush-compat balancer mode: per-device weight-set descent.

Parity with the reference's second balancer mode (upstream
``src/pybind/mgr/balancer/module.py :: do_crush_compat`` over
``CrushWrapper::choose_args``): instead of emitting pg_upmap_items, it
maintains an alternate per-item weight set (the "compat" choose_args)
that placement itself consumes, nudging each device's effective weight
toward its fair PG share.  Old clients that predate pg-upmap support
still see balanced placement because the weight set travels with the
crush map.

TPU-first shape: the reference trial-remaps through its C++ mapper per
iteration; here each iteration is one device batch remap per pool
(the compiled pool program is shape-stable under weight-set edits, so
iterations only rebuild the input pack — no retrace).
"""

from __future__ import annotations

import numpy as np

from ..osdmap.map import OSDMap
from ..osdmap.mapping import OSDMapMapping
from .upmap import expected_pg_share

COMPAT_WEIGHT_SET = "compat"


def _leaf_positions(crush) -> dict[int, tuple[int, int]]:
    """osd id -> (bucket id, index within bucket)."""
    pos: dict[int, tuple[int, int]] = {}
    for bid, b in crush.buckets.items():
        for idx, item in enumerate(b.items):
            if item >= 0:
                pos[item] = (bid, idx)
    return pos


def _propagate_sums(crush, name: str) -> None:
    """Recompute every weight-set entry for bucket children as the sum
    of the child's own weight-set row (straw2 parents select children
    proportionally to these, so sums must stay consistent)."""
    per = crush.choose_args[name]
    memo: dict[int, int] = {}

    def subtree_sum(bid: int) -> int:
        if bid in memo:
            return memo[bid]
        b = crush.buckets[bid]
        row = per[bid]
        total = 0
        for idx, item in enumerate(b.items):
            if item < 0:
                row[idx] = subtree_sum(item)
            total += row[idx]
        memo[bid] = total
        return total

    for bid in crush.buckets:
        subtree_sum(bid)


def do_crush_compat(
    m: OSDMap,
    pools: list[int] | None = None,
    max_iterations: int = 25,
    step: float = 0.5,
    max_deviation: float = 1.0,
    mapping: OSDMapMapping | None = None,
) -> bool:
    """Optimize the compat weight set; returns True if it changed.

    Each iteration: remap every pool on device with the current weight
    set, aggregate per-OSD actual vs fair-share PG counts, move each
    device's weight-set weight a ``step`` fraction toward
    ``actual/target`` correction, re-propagate bucket sums, and keep
    the best state seen (the reference's keep-if-better retry loop).
    """
    crush = m.crush
    mapping = mapping or OSDMapMapping(m)
    pool_ids = pools or sorted(m.pools)
    n_osd = max(m.max_osd, 1)
    created = COMPAT_WEIGHT_SET not in crush.choose_args
    if created:
        crush.create_choose_args(COMPAT_WEIGHT_SET)
    initial = {
        bid: list(row)
        for bid, row in crush.choose_args[COMPAT_WEIGHT_SET].items()
    }
    leaf_pos = _leaf_positions(crush)
    up = np.fromiter((m.is_up(o) for o in range(n_osd)), bool, count=n_osd)

    def measure() -> tuple[np.ndarray, np.ndarray]:
        counts = np.zeros(n_osd, np.float64)
        target = np.zeros(n_osd, np.float64)
        for pid in pool_ids:
            pool = m.pools[pid]
            expect = expected_pg_share(m, pool, n_osd)
            if expect is None:
                continue
            mapping.update(pid)
            counts += mapping.pg_counts_by_osd(pid, acting=False)
            target += expect
        return counts, target

    best_rows: dict[int, list[int]] | None = None
    best_worst = np.inf
    worst = 0.0
    # one extra trip so the last mutation still gets measured
    for it in range(max_iterations + 1):
        counts, target = measure()
        active = (target > 0) & up
        if not active.any():
            break
        dev = counts - target
        worst = float(np.abs(dev[active]).max(initial=0.0))
        if worst < best_worst:
            best_worst = worst
            best_rows = {
                bid: list(row)
                for bid, row in crush.choose_args[COMPAT_WEIGHT_SET].items()
            }
        if worst <= max_deviation or it == max_iterations:
            break
        per = crush.choose_args[COMPAT_WEIGHT_SET]
        for osd in np.nonzero(active)[0]:
            t, a = target[osd], counts[osd]
            ratio = min(t / a, 4.0) if a > 0 else 4.0
            bid, idx = leaf_pos[int(osd)]
            cur = per[bid][idx]
            neww = int(round(cur * (1.0 - step + step * ratio)))
            per[bid][idx] = max(neww, 1)
        _propagate_sums(crush, COMPAT_WEIGHT_SET)
        crush._mutated()

    # the loop always ends on a measured state (mutate -> re-measure),
    # so the last measured worst is the final worst; restore the best
    # state when the descent ended somewhere worse
    if best_rows is not None and worst > best_worst:
        crush.choose_args[COMPAT_WEIGHT_SET] = {
            bid: list(row) for bid, row in best_rows.items()
        }
        crush._mutated()

    changed = crush.choose_args[COMPAT_WEIGHT_SET] != initial
    if created and not changed:
        crush.rm_choose_args(COMPAT_WEIGHT_SET)
    return changed
