"""Upmap optimizer: deviation-minimizing pg_upmap_items search.

Equivalent of the reference's ``OSDMap::calc_pg_upmaps`` (upstream
``src/osd/OSDMap.cc``), consumed there by the mgr balancer module and
``osdmaptool --upmap``: compute each OSD's expected PG share from CRUSH
weights, then greedily move single replicas from the most-overfull OSD
to compatible underfull OSDs via ``pg_upmap_items``, until the worst
deviation is within ``max_deviation`` or no further progress.

TPU-native structure: the full-map remap (the expensive part the
reference runs on the ``ParallelPGMapper`` threadpool) is one device
batch launch (:mod:`ceph_tpu.osdmap.mapping`), re-run per round with the
trial upmap tables as *traced inputs* (no recompile); candidate scoring
is vectorized on host numpy over all (pg, from, to) moves at once
rather than the reference's per-candidate trial loop.
"""

from __future__ import annotations

import numpy as np

from ..crush.map import ITEM_NONE, CrushMap
from ..osdmap.map import Incremental, OSDMap, PGId, Pool
from ..osdmap.mapping import OSDMapMapping


def crush_device_weights(crush: CrushMap, rule_id: int, n_osd: int) -> np.ndarray:
    """Effective CRUSH weight per OSD under the rule's TAKE root."""
    from ..crush.map import OP_TAKE

    rule = crush.rules[rule_id]
    roots = [s.arg1 for s in rule.steps if s.op == OP_TAKE]
    w = np.zeros(n_osd, np.float64)

    def walk(item: int, bucket_weight: int) -> None:
        if item >= 0:
            if item < n_osd:
                w[item] += bucket_weight / 0x10000
            return
        b = crush.buckets[item]
        for it, iw in zip(b.items, b.item_weights):
            walk(it, iw)

    for r in roots:
        walk(r, 0)
    return w


def failure_domains(crush: CrushMap, rule_id: int, n_osd: int) -> np.ndarray:
    """Failure-domain id for each OSD under the rule (its ancestor of
    the rule's chooseleaf/choose type); domain -1 = unplaced."""
    from ..crush.map import (
        OP_CHOOSE_FIRSTN,
        OP_CHOOSE_INDEP,
        OP_CHOOSELEAF_FIRSTN,
        OP_CHOOSELEAF_INDEP,
    )

    rule = crush.rules[rule_id]
    fd_type = 0
    for s in rule.steps:
        if s.op in (
            OP_CHOOSE_FIRSTN,
            OP_CHOOSE_INDEP,
            OP_CHOOSELEAF_FIRSTN,
            OP_CHOOSELEAF_INDEP,
        ):
            fd_type = s.arg2
            break
    dom = np.full(n_osd, -1, np.int64)
    if fd_type == 0:
        # failure domain is the device itself
        dom[:] = np.arange(n_osd)
        return dom

    def walk(item: int, current: int) -> None:
        if item >= 0:
            if item < n_osd:
                dom[item] = current
            return
        b = crush.buckets[item]
        nxt = b.id if b.type_id == fd_type else current
        for it in b.items:
            walk(it, nxt)

    for bid, b in crush.buckets.items():
        if crush.parent_of(bid) is None:
            walk(bid, -1)
    return dom


def expected_pg_share(m: OSDMap, pool: Pool, n_osd: int) -> np.ndarray | None:
    """Per-OSD fair share of the pool's PG replicas (crush weight x
    reweight proportional); None if the rule subtree has no weight.
    Shared between the optimizer and the balancer's Eval so they agree
    on what 'balanced' means."""
    cw = crush_device_weights(m.crush, pool.crush_rule, n_osd)
    cw *= np.asarray(m.osd_weight, np.float64)[:n_osd] / 0x10000
    total = cw.sum()
    if total <= 0:
        return None
    return pool.pg_num * pool.size * cw / total


def calc_pg_upmaps(
    m: OSDMap,
    max_deviation: float = 1.0,
    max_entries: int = 100,
    pools: list[int] | None = None,
    mapping: OSDMapMapping | None = None,
) -> Incremental:
    """Compute pg_upmap_items moves; returns an Incremental (possibly
    empty).  ``max_deviation`` is in PGs, like the reference's
    ``upmap_max_deviation``.

    Trial moves are staged in a scratch upmap table on the SAME map
    object (restored on exit), so the already-compiled pool programs
    are reused — only the upmap input arrays change between rounds.
    The Incremental is diffed from the final validated trial state, so
    the committed epoch always equals what the optimizer scored.
    """
    inc = Incremental(epoch=m.epoch + 1)
    pool_ids = pools or sorted(m.pools)
    mapping = mapping or OSDMapMapping(m)
    n_osd = max(m.max_osd, 1)
    entries = 0
    original_items = m.pg_upmap_items

    for pool_id in pool_ids:
        pool = m.pools[pool_id]
        expect = expected_pg_share(m, pool, n_osd)
        if expect is None:
            continue
        cw = crush_device_weights(m.crush, pool.crush_rule, n_osd)
        cw *= np.asarray(m.osd_weight, np.float64)[:n_osd] / 0x10000
        dom = failure_domains(m.crush, pool.crush_rule, n_osd)

        mapping.update(pool_id)
        base_counts = mapping.pg_counts_by_osd(pool_id, acting=False)

        pool_entries = 0
        trial_items = dict(original_items)
        m.pg_upmap_items = trial_items  # staged; restored below
        try:
            for _round in range(max_entries):
                if entries + pool_entries >= max_entries:
                    break
                mapping.update(pool_id)
                up_all, _, _, _ = mapping._results[pool_id]
                counts = mapping.pg_counts_by_osd(pool_id, acting=False)
                deviation = counts - expect
                if deviation.max() <= max_deviation:
                    break
                # candidate moves: every pg replica on the most-overfull
                # osd, to every underfull osd in a compatible domain
                over = int(np.argmax(deviation))
                under = np.nonzero((deviation < -1e-9) & (cw > 0))[0]
                if len(under) == 0:
                    under = np.nonzero(
                        (deviation < deviation.max() - 1) & (cw > 0)
                    )[0]
                if len(under) == 0:
                    break
                pgs_on_over = np.nonzero((up_all == over).any(axis=1))[0]
                best = None  # (gain, pg, frm, to)
                for ps in pgs_on_over:
                    row = up_all[ps]
                    row_valid = row[row != ITEM_NONE]
                    used_doms = {int(dom[o]) for o in row_valid if o < n_osd}
                    frm_dom = int(dom[over])
                    existing = trial_items.get(PGId(pool_id, int(ps)), ())
                    if len(existing) >= 4:  # keep per-pg item lists short
                        continue
                    for to in under:
                        to = int(to)
                        if to in row_valid or not m.is_up(to):
                            continue
                        to_dom = int(dom[to])
                        if to_dom != frm_dom and to_dom in used_doms:
                            continue  # would double up a failure domain
                        gain = deviation[over] - deviation[to]
                        if best is None or gain > best[0]:
                            best = (float(gain), int(ps), over, to)
                if best is None:
                    break
                _, ps, frm, to = best
                pg = PGId(pool_id, ps)
                items = list(trial_items.get(pg, ()))
                # collapse chains: a->b then b->c becomes a->c
                for idx, (f0, t0) in enumerate(items):
                    if t0 == frm:
                        items[idx] = (f0, to)
                        break
                else:
                    items.append((frm, to))
                items = [(f, t) for f, t in items if f != t]
                if items:
                    trial_items[pg] = tuple(items)
                else:
                    trial_items.pop(pg, None)
                pool_entries += 1

            # validation: trial deviation must not be worse than base
            mapping.update(pool_id)
            final_counts = mapping.pg_counts_by_osd(pool_id, acting=False)
        finally:
            m.pg_upmap_items = original_items
            mapping.update(pool_id)  # restore cached results to reality

        if pool_entries == 0:
            continue
        if np.abs(final_counts - expect).max() > np.abs(
            base_counts - expect
        ).max():
            continue  # reject this pool's moves wholesale
        entries += pool_entries
        # diff trial vs live state for this pool only
        for pg in set(trial_items) | set(original_items):
            if pg.pool != pool_id:
                continue
            new = trial_items.get(pg)
            old = original_items.get(pg)
            if new == old:
                continue
            if new:
                inc.new_pg_upmap_items[pg] = new
            else:
                inc.old_pg_upmap_items.append(pg)
    return inc
