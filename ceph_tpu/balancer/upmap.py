"""Upmap optimizer: deviation-minimizing pg_upmap_items search.

Equivalent of the reference's ``OSDMap::calc_pg_upmaps`` (upstream
``src/osd/OSDMap.cc``), consumed there by the mgr balancer module and
``osdmaptool --upmap``: compute each OSD's expected PG share from CRUSH
weights, then greedily move single replicas from the most-overfull OSD
to compatible underfull OSDs via ``pg_upmap_items``, until the worst
deviation is within ``max_deviation`` or no further progress.

TPU-native structure: the full-map remap (the expensive part the
reference runs on the ``ParallelPGMapper`` threadpool) is one device
batch launch (:mod:`ceph_tpu.osdmap.mapping`), re-run once per round
with the trial upmap tables as *traced inputs* (no recompile).  Within
a round, candidate scoring really is vectorized: every (pg, from, to)
move out of every overfull OSD is scored as numpy array ops
(:func:`_score_candidate_moves`), then a whole batch of compatible
moves is accepted greedily against a simulated deviation vector, so
one device launch validates many moves instead of one launch per move.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from ..common.log import get_logger
from ..crush.map import ITEM_NONE, CrushMap
from ..osdmap.map import Incremental, OSDMap, PGId, Pool
from ..osdmap.mapping import OSDMapMapping

_LOG = get_logger("balancer")

# Candidate-scoring truncation bounds: the [R, S, U] broadcasts in
# _score_candidate_moves would blow past 1 GB unbounded at
# 10k-OSD/10k-PG scale, so rounds keep the worst rows and neediest
# targets — exactly the moves a round would accept anyway.  Module
# level so tests can shrink them and pin that convergence survives
# truncation (tests/test_balancer_scale.py).
MAX_ROWS = 8192
MAX_UNDER = 256

# sentinel failure-domain id for an invalid row slot (matches no real
# domain, including the -1 "unplaced" domain)
_DOM_NONE = np.int64(-(2**31))

#: hierarchy-walk memo for crush_device_weights / failure_domains,
#: keyed per (crush map identity, rule, width): both walks are pure
#: functions of the map revision, and calc_pg_upmaps calls them per
#: pool per invocation — on a 10k-OSD map the recursive Python walk
#: costs more than the device launches it feeds.  crush.uid is
#: process-unique (never reused) and crush.version bumps on every
#: mutation, so a stale hit is impossible.
_HIER_CACHE: dict = {}
_HIER_CACHE_MAX = 256


def _hier_cached(kind: str, crush: CrushMap, rule_id: int, n_osd: int, build):
    key = (kind, crush.uid, crush.version, rule_id, n_osd)
    hit = _HIER_CACHE.get(key)
    if hit is None:
        if len(_HIER_CACHE) >= _HIER_CACHE_MAX:
            _HIER_CACHE.clear()
        hit = _HIER_CACHE[key] = build()
    # callers scale/overwrite the result in place (expected_pg_share's
    # reweight multiply) — hand out a copy, never the cached array
    return hit.copy()


def crush_device_weights(crush: CrushMap, rule_id: int, n_osd: int) -> np.ndarray:
    """Effective CRUSH weight per OSD under the rule's TAKE root.
    Memoized per (map revision, rule, width); returns a fresh copy."""
    return _hier_cached(
        "weights", crush, rule_id, n_osd,
        lambda: _crush_device_weights_walk(crush, rule_id, n_osd),
    )


def _crush_device_weights_walk(
    crush: CrushMap, rule_id: int, n_osd: int
) -> np.ndarray:
    from ..crush.map import OP_TAKE

    rule = crush.rules[rule_id]
    roots = [s.arg1 for s in rule.steps if s.op == OP_TAKE]
    w = np.zeros(n_osd, np.float64)

    def walk(item: int, bucket_weight: int) -> None:
        if item >= 0:
            if item < n_osd:
                w[item] += bucket_weight / 0x10000
            return
        b = crush.buckets[item]
        for it, iw in zip(b.items, b.item_weights):
            walk(it, iw)

    for r in roots:
        walk(r, 0)
    return w


def failure_domains(crush: CrushMap, rule_id: int, n_osd: int) -> np.ndarray:
    """Failure-domain id for each OSD under the rule (its ancestor of
    the rule's chooseleaf/choose type); domain -1 = unplaced.
    Memoized per (map revision, rule, width); returns a fresh copy."""
    return _hier_cached(
        "domains", crush, rule_id, n_osd,
        lambda: _failure_domains_walk(crush, rule_id, n_osd),
    )


def _failure_domains_walk(
    crush: CrushMap, rule_id: int, n_osd: int
) -> np.ndarray:
    from ..crush.map import (
        OP_CHOOSE_FIRSTN,
        OP_CHOOSE_INDEP,
        OP_CHOOSELEAF_FIRSTN,
        OP_CHOOSELEAF_INDEP,
    )

    rule = crush.rules[rule_id]
    fd_type = 0
    for s in rule.steps:
        if s.op in (
            OP_CHOOSE_FIRSTN,
            OP_CHOOSE_INDEP,
            OP_CHOOSELEAF_FIRSTN,
            OP_CHOOSELEAF_INDEP,
        ):
            fd_type = s.arg2
            break
    dom = np.full(n_osd, -1, np.int64)
    if fd_type == 0:
        # failure domain is the device itself
        dom[:] = np.arange(n_osd)
        return dom

    def walk(item: int, current: int) -> None:
        if item >= 0:
            if item < n_osd:
                dom[item] = current
            return
        b = crush.buckets[item]
        nxt = b.id if b.type_id == fd_type else current
        for it in b.items:
            walk(it, nxt)

    for bid, b in crush.buckets.items():
        if crush.parent_of(bid) is None:
            walk(bid, -1)
    return dom


def expected_pg_share(m: OSDMap, pool: Pool, n_osd: int) -> np.ndarray | None:
    """Per-OSD fair share of the pool's PG replicas (crush weight x
    reweight proportional); None if the rule subtree has no weight.
    Shared between the optimizer and the balancer's Eval so they agree
    on what 'balanced' means."""
    cw = crush_device_weights(m.crush, pool.crush_rule, n_osd)
    cw *= np.asarray(m.osd_weight, np.float64)[:n_osd] / 0x10000
    total = cw.sum()
    if total <= 0:
        return None
    return pool.pg_num * pool.size * cw / total


@dataclass
class UpmapRunStats:
    """Device-launch accounting for one calc_pg_upmaps invocation.

    ``launches_per_round`` is the acceptance-criterion headline: with
    the vmapped scorer every optimization round costs exactly one
    pool-remap launch plus one candidate-scoring launch (the greedy
    acceptance and entry GC are pure host bookkeeping), so the value is
    =< 2 regardless of map size.  ``candidates_scored`` counts the
    (pg-row x underfull-target) pairs evaluated, the bench's
    candidate-evals/s numerator."""

    rounds: int = 0
    mapping_launches: int = 0
    score_launches: int = 0
    np_score_calls: int = 0
    candidates_scored: int = 0
    pools: int = 0

    @property
    def launches_per_round(self) -> float:
        if self.rounds == 0:
            return 0.0
        return (self.mapping_launches + self.score_launches) / self.rounds

    def as_dict(self) -> dict:
        return {
            "rounds": self.rounds,
            "mapping_launches": self.mapping_launches,
            "score_launches": self.score_launches,
            "np_score_calls": self.np_score_calls,
            "candidates_scored": self.candidates_scored,
            "pools": self.pools,
            "launches_per_round": self.launches_per_round,
        }


#: stats of the most recent calc_pg_upmaps call (benches read this)
LAST_RUN_STATS = UpmapRunStats()


def _vmapped_scoring() -> bool:
    """Whether candidate scoring runs as one jitted launch per round
    (default) or on the host numpy reference path
    (``CEPH_TPU_VMAPPED_UPMAP=0``).  Both paths emit the identical
    candidate stream — the numpy path is kept as the differential
    reference and the no-jax escape hatch."""
    return os.environ.get("CEPH_TPU_VMAPPED_UPMAP", "1") != "0"


def _candidate_rows(
    up_all: np.ndarray,
    deviation: np.ndarray,
    underfull: np.ndarray,
    n_osd: int,
):
    """Host-side row/target selection shared by both scoring paths:
    picks each PG's most-overfull member, keeps rows with positive
    deviation, and applies the worst-first / neediest-first truncation
    bounds.  This is [P, S] work — trivial next to the [R, S, U]
    scoring broadcasts — and keeping it on the host guarantees the two
    paths score the exact same candidate set in the exact same order."""
    valid = (up_all != ITEM_NONE) & (up_all >= 0) & (up_all < n_osd)
    up_c = np.clip(up_all, 0, n_osd - 1)
    dev_row = np.where(valid, deviation[up_c], -np.inf)  # [P, S]
    frm_slot = dev_row.argmax(axis=1)  # [P]
    rows = np.arange(up_all.shape[0])
    frm = up_c[rows, frm_slot]  # [P]
    frm_dev = dev_row[rows, frm_slot]  # [P]
    r_sel = np.nonzero(frm_dev > 0.0)[0]
    if len(r_sel) == 0 or len(underfull) == 0:
        return valid, up_c, frm, frm_dev, r_sel[:0], underfull[:0]
    if len(r_sel) > MAX_ROWS:
        _LOG.info(
            "candidate truncation: keeping %d of %d overfull PG rows "
            "(worst-first); later rounds revisit the rest",
            MAX_ROWS, len(r_sel),
        )
        worst = np.argsort(-frm_dev[r_sel], kind="stable")[:MAX_ROWS]
        r_sel = r_sel[worst]
    if len(underfull) > MAX_UNDER:
        _LOG.info(
            "candidate truncation: keeping %d of %d underfull targets "
            "(neediest-first)",
            MAX_UNDER, len(underfull),
        )
        neediest = np.argsort(deviation[underfull], kind="stable")[:MAX_UNDER]
        underfull = underfull[neediest]
    return valid, up_c, frm, frm_dev, r_sel, underfull


def _empty_candidates():
    empty = np.empty(0, np.int64)
    return empty.astype(np.float64), empty, empty, empty


def _score_candidate_moves(
    up_all: np.ndarray,
    deviation: np.ndarray,
    dom: np.ndarray,
    underfull: np.ndarray,
    max_deviation: float,
    n_osd: int,
    stats: UpmapRunStats | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized scoring of every (pg, from, to) candidate move.

    For each PG row the ``from`` is its most-overfull member (the
    reference empties the most-overfull OSD first); ``to`` ranges over
    all underfull OSDs.  Returns flat arrays (gain, pg, frm, to) of
    admissible candidates, unsorted; a candidate is admissible when

    - the move strictly improves balance (gain = dev[frm]-dev[to] > 1),
    - it addresses an actual violation: frm above +max_deviation or
      to below -max_deviation (both sides count — an OSD stuck 4 PGs
      under its share is as unbalanced as one 4 over),
    - ``to`` is not already in the row, and
    - ``to``'s failure domain differs from ``frm``'s only if it is not
      already used by another member (the reference's domain guard).

    Dispatches to the one-launch jitted scorer by default (the [R,S,U]
    broadcasts below are the per-round hot loop); the numpy path is
    the bit-identical reference (``CEPH_TPU_VMAPPED_UPMAP=0``).  Both
    produce the same flat candidate ordering — row-major over (worst
    rows, underfull targets) — which the caller's stable gain sort
    depends on, so the final upmap set is path-independent.
    """
    if _vmapped_scoring():
        return _score_candidate_moves_vmapped(
            up_all, deviation, dom, underfull, max_deviation, n_osd, stats
        )
    return _score_candidate_moves_np(
        up_all, deviation, dom, underfull, max_deviation, n_osd, stats
    )


def _score_candidate_moves_np(
    up_all: np.ndarray,
    deviation: np.ndarray,
    dom: np.ndarray,
    underfull: np.ndarray,
    max_deviation: float,
    n_osd: int,
    stats: UpmapRunStats | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Host numpy reference scorer (see _score_candidate_moves)."""
    valid, up_c, frm, frm_dev, r_sel, underfull = _candidate_rows(
        up_all, deviation, underfull, n_osd
    )
    if len(r_sel) == 0 or len(underfull) == 0:
        return _empty_candidates()
    if stats is not None:
        stats.np_score_calls += 1
        stats.candidates_scored += len(r_sel) * len(underfull)
    sub_up = up_c[r_sel]  # [R, S]
    sub_valid = valid[r_sel]
    sub_frm = frm[r_sel]  # [R]
    # to already in the row?
    in_row = (
        (sub_up[:, :, None] == underfull[None, None, :]) & sub_valid[:, :, None]
    ).any(axis=1)  # [R, U]
    # failure-domain guard
    row_doms = np.where(sub_valid, dom[sub_up], _DOM_NONE)  # [R, S]
    to_dom = dom[underfull]  # [U]
    dom_used = (row_doms[:, :, None] == to_dom[None, None, :]).any(axis=1)
    dom_conflict = dom_used & (to_dom[None, :] != dom[sub_frm][:, None])
    to_dev = deviation[underfull]  # [U]
    gain = frm_dev[r_sel][:, None] - to_dev[None, :]  # [R, U]
    violates = (frm_dev[r_sel][:, None] > max_deviation) | (
        to_dev[None, :] < -max_deviation
    )
    ok = ~in_row & ~dom_conflict & (gain > 1.0) & violates
    ri, ui = np.nonzero(ok)
    return (
        gain[ri, ui],
        r_sel[ri].astype(np.int64),
        sub_frm[ri].astype(np.int64),
        underfull[ui].astype(np.int64),
    )


@jax.jit
def _score_kernel(
    sub_up,      # [R, S] i64, row members clipped to [0, n_osd)
    sub_valid,   # [R, S] bool
    sub_frm,     # [R]    i64, most-overfull member per row
    sub_frm_dev, # [R]    f64, its deviation
    row_ok,      # [R]    bool, False on padding rows
    underfull,   # [U]    i64, target OSDs (0 on padding)
    u_ok,        # [U]    bool, False on padding targets
    deviation,   # [N]    f64
    dom,         # [N]    i64 failure-domain ids
    max_deviation,  # f64 scalar
):
    """One-launch candidate scorer: the [R,S,U] admissibility
    broadcasts of _score_candidate_moves_np as a single jitted
    program over padded fixed shapes.  All arithmetic is float64
    gather/subtract/compare — IEEE-identical to the numpy reference,
    which is what makes the two paths produce the same candidate set
    bit-for-bit (the package-wide x64 shim keeps f64 live under jit).

    Shapes are padded to per-pool constants (R = min(MAX_ROWS, pg_num),
    U = min(MAX_UNDER, n_osd)), so every round of every epoch reuses
    one compiled program."""
    to_dev = deviation[underfull]  # [U]
    in_row = (
        (sub_up[:, :, None] == underfull[None, None, :])
        & sub_valid[:, :, None]
    ).any(axis=1)  # [R, U]
    row_doms = jnp.where(sub_valid, dom[sub_up], jnp.int64(_DOM_NONE))
    to_dom = dom[underfull]  # [U]
    dom_used = (row_doms[:, :, None] == to_dom[None, None, :]).any(axis=1)
    dom_conflict = dom_used & (to_dom[None, :] != dom[sub_frm][:, None])
    gain = sub_frm_dev[:, None] - to_dev[None, :]  # [R, U]
    violates = (sub_frm_dev[:, None] > max_deviation) | (
        to_dev[None, :] < -max_deviation
    )
    ok = (
        ~in_row
        & ~dom_conflict
        & (gain > 1.0)
        & violates
        & row_ok[:, None]
        & u_ok[None, :]
    )
    return gain, ok


def _score_candidate_moves_vmapped(
    up_all: np.ndarray,
    deviation: np.ndarray,
    dom: np.ndarray,
    underfull: np.ndarray,
    max_deviation: float,
    n_osd: int,
    stats: UpmapRunStats | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """One-launch scorer: batches ALL candidate (pg, from, to) triples
    of a round into a single _score_kernel dispatch, padded to fixed
    per-pool shapes so rounds never recompile.  The flat candidate
    stream (order included) is identical to the numpy path's."""
    valid, up_c, frm, frm_dev, r_sel, underfull = _candidate_rows(
        up_all, deviation, underfull, n_osd
    )
    n_r, n_u = len(r_sel), len(underfull)
    if n_r == 0 or n_u == 0:
        return _empty_candidates()
    r_cap = min(MAX_ROWS, up_all.shape[0])
    u_cap = min(MAX_UNDER, n_osd)

    def _pad(a: np.ndarray, cap: int, fill) -> np.ndarray:
        out = np.full((cap,) + a.shape[1:], fill, a.dtype)
        out[: a.shape[0]] = a
        return out

    sub_up = _pad(up_c[r_sel].astype(np.int64), r_cap, 0)
    sub_valid = _pad(valid[r_sel], r_cap, False)
    sub_frm = _pad(frm[r_sel].astype(np.int64), r_cap, 0)
    sub_frm_dev = _pad(frm_dev[r_sel], r_cap, 0.0)
    row_ok = np.zeros(r_cap, bool)
    row_ok[:n_r] = True
    under_pad = _pad(underfull.astype(np.int64), u_cap, 0)
    u_ok = np.zeros(u_cap, bool)
    u_ok[:n_u] = True

    gain, ok = _score_kernel(
        sub_up, sub_valid, sub_frm, sub_frm_dev, row_ok,
        under_pad, u_ok,
        np.asarray(deviation, np.float64),
        np.asarray(dom, np.int64),
        np.float64(max_deviation),
    )
    if stats is not None:
        stats.score_launches += 1
        stats.candidates_scored += n_r * n_u
    gain = np.asarray(gain)
    ok = np.asarray(ok)
    ri, ui = np.nonzero(ok)  # row-major: same flat order as numpy path
    return (
        gain[ri, ui],
        r_sel[ri].astype(np.int64),
        sub_frm[ri],
        under_pad[ui],
    )


def calc_pg_upmaps(
    m: OSDMap,
    max_deviation: float = 1.0,
    max_entries: int = 100,
    pools: list[int] | None = None,
    mapping: OSDMapMapping | None = None,
    max_rounds: int = 16,
) -> Incremental:
    """Compute pg_upmap_items moves; returns an Incremental (possibly
    empty).  ``max_deviation`` is in PGs, like the reference's
    ``upmap_max_deviation``.

    Trial moves are staged in a scratch upmap table on the SAME map
    object (restored on exit), so the already-compiled pool programs
    are reused — only the upmap input arrays change between rounds.
    The Incremental is diffed from the final validated trial state, so
    the committed epoch always equals what the optimizer scored.
    """
    global LAST_RUN_STATS
    stats = UpmapRunStats()
    inc = Incremental(epoch=m.epoch + 1)
    pool_ids = pools or sorted(m.pools)
    mapping = mapping or OSDMapMapping(m)
    n_osd = max(m.max_osd, 1)
    entries = 0
    original_items = m.pg_upmap_items

    for pool_id in pool_ids:
        pool = m.pools[pool_id]
        expect = expected_pg_share(m, pool, n_osd)
        if expect is None:
            continue
        cw = crush_device_weights(m.crush, pool.crush_rule, n_osd)
        cw *= np.asarray(m.osd_weight, np.float64)[:n_osd] / 0x10000
        dom = failure_domains(m.crush, pool.crush_rule, n_osd)

        stats.pools += 1
        mapping.update(pool_id)
        base_counts = mapping.pg_counts_by_osd(pool_id, acting=False)

        pool_entries = 0
        pool_removed = 0
        # raw (pre-upmap) rows for every PG carrying entries, computed
        # in ONE batched CRUSH call (raw depends only on crush+weights,
        # constant during this optimization): the GC below simulates
        # _apply_upmap against them
        entry_ps = sorted({
            pg.ps for pg in original_items if pg.pool == pool_id
        })
        raw_rows: dict[int, list[int]] = (
            m.pg_to_raw_osds_batch(pool_id, entry_ps) if entry_ps else {}
        )
        trial_items = dict(original_items)
        m.pg_upmap_items = trial_items  # staged; restored below
        up_vec = np.fromiter(
            (m.is_up(o) for o in range(n_osd)), bool, count=n_osd
        )
        try:
            for _round in range(max_rounds):
                if entries + pool_entries >= max_entries:
                    break
                # ONE device launch per round re-maps the whole pool
                # with the trial upmap tables as inputs
                stats.rounds += 1
                stats.mapping_launches += 1
                mapping.update(pool_id)
                up_all, _, _, _ = mapping._results[pool_id]
                counts = mapping.pg_counts_by_osd(pool_id, acting=False)
                deviation = counts - expect
                # balanced means NO osd beyond +-max_deviation (weightless
                # devices excluded: they cannot receive PGs)
                weighted = cw > 0
                worst = max(
                    float(deviation[weighted].max(initial=0.0)),
                    float(-deviation[weighted & up_vec].min(initial=0.0)),
                )
                if worst <= max_deviation:
                    break
                # --- entry GC first: reverse existing trial entries
                # whose removal now helps balance.  Upmap entries are
                # mon-map state the reference treats as precious
                # (OSDMap::calc_pg_upmaps considers existing items for
                # removal before adding new ones); each reversal here is
                # a free rebalancing move that SHRINKS the table.
                pg_touched: set[int] = set()
                gc_removed = 0

                def _apply_pairs(raw: list[int], items) -> list[int]:
                    """Mirror _apply_upmap's sequential pair semantics:
                    each pair rewrites the first f in the EVOLVING row,
                    skipped when t already present or weight-zero."""
                    row = list(raw)
                    for f2, t in items:
                        if (
                            0 <= t < n_osd
                            and m.osd_weight[t] == 0
                        ):
                            continue
                        if t in row or f2 not in row:
                            continue
                        row[row.index(f2)] = t
                    return row

                for pg in list(trial_items):
                    if pg.pool != pool_id or pg.ps in pg_touched:
                        continue
                    raw = raw_rows.get(pg.ps)
                    if raw is None:  # entry added this call; rare
                        raw = raw_rows[pg.ps] = m.pg_to_raw_osds_batch(
                            pool_id, [pg.ps]
                        )[pg.ps]
                    # _apply_upmap applies pairs ON TOP of a full
                    # pg_upmap override when one is in effect
                    um = m.pg_upmap.get(pg)
                    if um is not None:
                        void = any(
                            0 <= o < n_osd and m.osd_weight[o] == 0
                            for o in um
                            if o != ITEM_NONE
                        )
                        if void:
                            continue  # items blocked entirely; leave
                        raw = list(um)
                    row = up_all[pg.ps]
                    rowv = row[(row != ITEM_NONE) & (row >= 0) & (row < n_osd)]
                    items = list(trial_items[pg])
                    changed = False
                    for idx in range(len(items) - 1, -1, -1):
                        f, t2 = items[idx]
                        if not (0 <= f < n_osd and 0 <= t2 < n_osd):
                            continue
                        # what does removing this pair actually change?
                        # (pairs interact through the evolving row, so
                        # test by re-simulating _apply_upmap)
                        with_pair = _apply_pairs(raw, items)
                        without = _apply_pairs(
                            raw, items[:idx] + items[idx + 1:]
                        )
                        delta = [
                            (a, b)
                            for a, b in zip(with_pair, without)
                            if a != b
                        ]
                        if not delta:
                            # inert entry: drop for free (upstream
                            # clean_pg_upmaps), no deviation change
                            del items[idx]
                            gc_removed += 1
                            changed = True
                            continue
                        if len(delta) != 1:
                            continue  # cascading effect: leave alone
                        lose, gain_o = delta[0]
                        if not (0 <= lose < n_osd and 0 <= gain_o < n_osd):
                            continue
                        # removal moves one replica lose -> gain_o
                        if deviation[lose] - deviation[gain_o] <= 1.0:
                            continue
                        if (
                            deviation[lose] <= max_deviation
                            and deviation[gain_o] >= -max_deviation
                        ):
                            continue
                        if not (up_vec[gain_o] and cw[gain_o] > 0):
                            continue
                        if gain_o in rowv:
                            continue
                        others = rowv[rowv != lose]
                        if dom[gain_o] != -1 and (
                            dom[others] == dom[gain_o]
                        ).any():
                            continue
                        del items[idx]
                        deviation[lose] -= 1.0
                        deviation[gain_o] += 1.0
                        # keep the effective row current for the next
                        # removal's in-row/domain guards on this PG
                        rowv = np.where(rowv == lose, gain_o, rowv)
                        gc_removed += 1
                        changed = True
                    if changed:
                        if items:
                            trial_items[pg] = tuple(items)
                        else:
                            trial_items.pop(pg, None)
                        pg_touched.add(pg.ps)

                under = np.nonzero((deviation < -1e-9) & (cw > 0) & up_vec)[0]
                if len(under) == 0:
                    under = np.nonzero(
                        (deviation < deviation.max() - 1) & (cw > 0) & up_vec
                    )[0]
                if len(under) == 0 and gc_removed == 0:
                    break
                gains, pgs, frms, tos = _score_candidate_moves(
                    up_all, deviation, dom, under, max_deviation, n_osd,
                    stats=stats,
                )
                if len(gains) == 0 and gc_removed == 0:
                    break
                # Greedy batched acceptance against a simulated deviation
                # vector: each accepted move shifts one PG replica, so
                # dev[frm] -= 1 and dev[to] += 1.  One move per PG per
                # round; a move must still help at acceptance time.
                pool_removed += gc_removed
                order = np.argsort(-gains, kind="stable")
                dev_sim = deviation.copy()
                accepted = gc_removed
                for ci in order:
                    if entries + pool_entries >= max_entries:
                        break
                    ps, frm, to = int(pgs[ci]), int(frms[ci]), int(tos[ci])
                    if ps in pg_touched:
                        continue
                    if dev_sim[frm] - dev_sim[to] <= 1.0:
                        continue  # move no longer helps
                    if (
                        dev_sim[frm] <= max_deviation
                        and dev_sim[to] >= -max_deviation
                    ):
                        continue  # neither side still violates
                    pg = PGId(pool_id, ps)
                    items = list(trial_items.get(pg, ()))
                    if len(items) >= 4:  # keep per-pg item lists short
                        continue
                    # collapse chains: a->b then b->c becomes a->c
                    for idx, (f0, t0) in enumerate(items):
                        if t0 == frm:
                            items[idx] = (f0, to)
                            break
                    else:
                        items.append((frm, to))
                    items = [(f, t) for f, t in items if f != t]
                    if items:
                        trial_items[pg] = tuple(items)
                    else:
                        trial_items.pop(pg, None)
                    pg_touched.add(ps)
                    dev_sim[frm] -= 1.0
                    dev_sim[to] += 1.0
                    pool_entries += 1
                    accepted += 1
                if accepted == 0:
                    break

            # validation: trial deviation must not be worse than base
            mapping.update(pool_id)
            final_counts = mapping.pg_counts_by_osd(pool_id, acting=False)
        finally:
            m.pg_upmap_items = original_items
            mapping.update(pool_id)  # restore cached results to reality

        if pool_entries == 0 and pool_removed == 0:
            continue
        if np.abs(final_counts - expect).max() > np.abs(
            base_counts - expect
        ).max():
            continue  # reject this pool's moves wholesale
        entries += pool_entries
        # diff trial vs live state for this pool only; sorted so the
        # incremental's entry order is rank- and hashseed-identical
        for pg in sorted(set(trial_items) | set(original_items)):
            if pg.pool != pool_id:
                continue
            new = trial_items.get(pg)
            old = original_items.get(pg)
            if new == old:
                continue
            if new:
                inc.new_pg_upmap_items[pg] = new
            else:
                inc.old_pg_upmap_items.append(pg)
    LAST_RUN_STATS = stats
    return inc
