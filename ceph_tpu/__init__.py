"""ceph_tpu — TPU-native batch CRUSH placement and erasure coding.

A from-scratch JAX/XLA/Pallas framework with the capabilities of the
reference storage stack's placement + erasure-coding slice (see
SURVEY.md): CRUSH straw2 placement vectorized over millions of objects,
OSDMap object->PG->OSD pipeline, upmap balancer, and Reed-Solomon /
bit-matrix erasure codes as MXU matmuls.

x64 note: CRUSH's straw2 draw is defined in 64-bit integer arithmetic
(48-bit fixed-point log divided by a 16.16 weight).  The package enables
JAX x64 mode at import so uint64 is available on all backends; all
framework arrays carry explicit dtypes, so user code is unaffected
except that 64-bit types become representable.
"""

from jax import config as _jax_config

# the ONE sanctioned global x64 toggle (everything else goes through
# the enable_x64 shim below — jaxlint J005 enforces that)
_jax_config.update("jax_enable_x64", True)  # jaxlint: disable=J005


def enable_x64(new_val: bool = True):
    """Context manager scoping x64 mode on or off (compat shim).

    ``jax.enable_x64`` was removed from the top-level namespace in JAX
    0.4.37; the supported spelling is ``jax.experimental.enable_x64``.
    Framework code that must trace with x64 scoped off (the Pallas
    kernels — Mosaic rejects i64 leaking into BlockSpec index maps)
    goes through this one shim so the next rename is a one-line fix.
    """
    # this function IS the shim jaxlint J005 points everyone at
    from jax.experimental import enable_x64 as _enable_x64  # jaxlint: disable=J005

    return _enable_x64(new_val)  # jaxlint: disable=J005


__version__ = "0.1.0"
